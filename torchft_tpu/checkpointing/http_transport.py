"""HTTP checkpoint transport: per-replica HTTP server streaming live weights.

Twin of the reference transport (``torchft/checkpointing/http_transport.py``):
every worker runs a threading HTTP server; ``metadata()`` is its URL; healing
peers fetch ``/checkpoint/{step}/full`` (or ``/checkpoint/{step}/{i}`` chunks
in parallel); the RWLock freezes the state dict while it is being serialized
so the train loop can't mutate weights mid-transfer
(``http_transport.py:181-202``).

Divergence from the reference: staging stores a serialization *plan* (the
tree skeleton + references to the immutable jax leaves; mutable numpy
leaves are snapshotted), and serving threads materialize one leaf at a time
while streaming it to the socket (the reference's incremental-save analog,
``_serialization.py:14-39``).  Peak extra host RSS during a heal send is
one leaf, not 1-2× the model; chunked fetches stream the byte range they
own the same way.  jax leaves are snapshotted on device at staging time so
a donating jit (e.g. HSDPTrainer's update) can't invalidate them while a
peer is still fetching.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
from io import BufferedWriter, RawIOBase
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, TypeVar
from urllib.request import urlopen

from torchft_tpu.checkpointing._rwlock import RWLock
from torchft_tpu.checkpointing.serialization import (
    PytreePlan,
    load_pytree,
    plan_pytree,
)
from torchft_tpu.checkpointing.transport import CheckpointTransport

logger = logging.getLogger(__name__)

T = TypeVar("T")


def _read_stream_into(resp, view: memoryview) -> None:
    """Drain exactly ``len(view)`` bytes from a response into ``view``."""
    off = 0
    while off < len(view):
        n = resp.readinto(view[off:])
        if not n:
            raise EOFError("truncated checkpoint response")
        off += n


class _RawSocketWriter(RawIOBase):
    """Adapts the handler's socket file to io.BufferedWriter."""

    def __init__(self, wfile) -> None:
        super().__init__()
        self._wfile = wfile

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        # honor the RawIOBase short-write contract: BufferedWriter retries
        # any remainder only if we report what was actually written
        return self._wfile.write(b)


class _ViewReader:
    """Minimal read/readinto stream over a memoryview (no BytesIO copy)."""

    def __init__(self, view: memoryview) -> None:
        self._view = view
        self._off = 0

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = len(self._view) - self._off
        out = bytes(self._view[self._off : self._off + n])
        self._off += len(out)
        return out

    def readinto(self, out) -> int:
        n = min(len(out), len(self._view) - self._off)
        out[:n] = self._view[self._off : self._off + n]
        self._off += n
        return n


class HTTPTransport(CheckpointTransport[T]):
    """Serve/fetch live checkpoints over HTTP.

    Args:
        timeout: default deadline for fetches.
        num_chunks: >0 splits the serialized state into N byte-ranges fetched
            by parallel threads (``http_transport.py:219-241``); 0 streams
            one ``full`` payload.
    """

    def __init__(self, timeout: float = 60.0, num_chunks: int = 0) -> None:
        self._timeout = timeout
        self._num_chunks = num_chunks
        self._lock = RWLock(timeout=timeout)
        self._staged: Optional[Dict[str, object]] = None  # step, chunks
        self._allowed = threading.Event()

        transport = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt: str, *args: object) -> None:
                logger.debug("http_transport: " + fmt, *args)

            def do_GET(self) -> None:
                parts = [p for p in self.path.split("/") if p]
                # /checkpoint/{step}/{full|i}
                if len(parts) != 3 or parts[0] != "checkpoint":
                    self.send_error(404, "unknown path")
                    return
                # Wait for a checkpoint to be staged rather than 404ing a
                # peer that raced ahead (the quorum guarantees it's coming).
                if not transport._allowed.wait(timeout=transport._timeout):
                    self.send_error(503, "no checkpoint staged")
                    return
                # the lock is only held to grab the plan reference — the
                # plan's leaves are self-contained snapshots, so streaming
                # happens lock-free and a concurrent disallow_checkpoint
                # (write lock, taken in the commit path) never waits on a
                # slow healer's socket
                with transport._lock.r_lock():
                    staged = transport._staged
                    plan: Optional[PytreePlan] = (
                        staged["plan"] if staged is not None else None  # type: ignore[assignment,index]
                    )
                    staged_step = staged["step"] if staged is not None else None
                if plan is None:
                    self.send_error(503, "no checkpoint staged")
                    return
                step = int(parts[1])
                if staged_step != step:
                    self.send_error(
                        404,
                        f"staged step {staged_step} != requested {step}",
                    )
                    return
                num_chunks = max(1, transport._num_chunks)
                chunk_size = -(-plan.total_len // num_chunks)
                if parts[2] == "full":
                    start, stop = 0, plan.total_len
                else:
                    idx = int(parts[2])
                    if idx >= num_chunks:
                        self.send_error(404, f"no chunk {idx}")
                        return
                    start = idx * chunk_size
                    stop = min(plan.total_len, start + chunk_size)
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Content-Length", str(stop - start))
                self.send_header("X-Num-Chunks", str(num_chunks))
                self.send_header("X-Total-Len", str(plan.total_len))
                self.end_headers()
                # streams leaf by leaf: only leaves overlapping [start, stop)
                # are ever materialized on host.  The handler's wfile is an
                # unbuffered socket writer; batching the plan's small frame
                # headers with the payloads into 1 MB writes avoids
                # per-frame syscalls
                buffered = BufferedWriter(
                    _RawSocketWriter(self.wfile), buffer_size=1 << 20
                )
                plan.write_range(start, stop, buffered)
                buffered.flush()

        class _Server(ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        # dual-stack like the reference's checkpoint server
        # (torchft/http.py:11-13): bind [::] with v6only off where the
        # kernel allows, so v4 and v6 healers both reach us
        v6_server = None
        try:
            _Server.address_family = socket.AF_INET6
            v6_server = _Server(("::", 0), _Handler, bind_and_activate=False)
            v6_server.socket.setsockopt(
                socket.IPPROTO_IPV6, socket.IPV6_V6ONLY, 0
            )
            v6_server.server_bind()
            v6_server.server_activate()
            self._server = v6_server
        except OSError:
            if v6_server is not None:
                v6_server.server_close()
            _Server.address_family = socket.AF_INET
            self._server = _Server(("0.0.0.0", 0), _Handler)
        self._port: int = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpuft_http_transport",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._port

    def metadata(self) -> str:
        return f"http://{socket.gethostname()}:{self._port}"

    def send_checkpoint(
        self, dst_ranks: List[int], step: int, state_dict: T, timeout: float
    ) -> None:
        """Stage a streaming plan under the write lock; serving threads
        materialize leaves lazily (bytes are generated per-request, never
        staged)."""
        plan = plan_pytree(state_dict, snapshot=True)
        with self._lock.w_lock(timeout=timeout):
            self._staged = {"step": step, "plan": plan}
        self._allowed.set()

    def disallow_checkpoint(self) -> None:
        self._allowed.clear()
        with self._lock.w_lock():
            self._staged = None

    def recv_checkpoint(
        self,
        src_rank: int,
        metadata: str,
        step: int,
        timeout: float,
        leaf_hook=None,
    ) -> T:
        """Fetch and deserialize a peer's live checkpoint.

        Default (num_chunks=0) is fully streaming: array payloads are read
        straight off the socket into preallocated arrays, and ``leaf_hook``
        (e.g. a ``jax.device_put`` with the healing replica's sharding) maps
        each leaf on arrival so its host copy dies immediately."""
        base = f"{metadata}/checkpoint/{step}"
        if self._num_chunks == 0:
            with urlopen(f"{base}/full", timeout=timeout) as resp:
                return load_pytree(resp, leaf_hook=leaf_hook)  # type: ignore[return-value]

        # chunked mode: parallel range fetches landing in one preallocated
        # buffer (no per-chunk bytes objects, no join copy)
        with urlopen(f"{base}/0", timeout=timeout) as resp:
            total = int(resp.headers.get("X-Num-Chunks", "1"))
            total_len = int(resp.headers["X-Total-Len"])
            chunk_size = -(-total_len // max(1, total))
            buf = bytearray(total_len)
            view = memoryview(buf)
            _read_stream_into(resp, view[: min(chunk_size, total_len)])

        done = [False] * total
        done[0] = True
        errors: List[BaseException] = []

        def _fetch(i: int) -> None:
            try:
                start = i * chunk_size
                stop = min(total_len, start + chunk_size)
                with urlopen(f"{base}/{i}", timeout=timeout) as r:
                    _read_stream_into(r, view[start:stop])
                done[i] = True
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                errors.append(e)

        threads = [
            threading.Thread(target=_fetch, args=(i,)) for i in range(1, total)
        ]
        deadline = time.monotonic() + timeout
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if errors:
            # a real fetch failure (404/refused) must not masquerade as a
            # timeout
            raise errors[0]
        if not all(done):
            raise TimeoutError("chunked checkpoint fetch timed out")
        return load_pytree(_ViewReader(view), leaf_hook=leaf_hook)  # type: ignore[return-value]

    def shutdown(self, wait: bool = True) -> None:
        self._server.shutdown()
        self._server.server_close()
