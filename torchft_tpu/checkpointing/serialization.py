"""Streaming serialization for pytrees of jax/numpy arrays.

The reference streams ``torch.save``-serialized state dicts
(``torchft/checkpointing/_serialization.py:14-39``); here the state is an
arbitrary pytree whose array leaves are jax Arrays or numpy arrays.  The
format separates the (pickled) tree skeleton from raw array payloads so
multi-MB tensors stream as straight buffer copies with no pickle overhead:

``TFTC`` magic + version, skeleton (pickle with array leaves replaced by
placeholders), then per-array: dtype tag, shape, raw little-endian bytes.

Like the reference (which pickles tensor metadata over its transports,
``pg_transport.py:32-146``), the skeleton uses pickle and therefore assumes
the same trust model: checkpoint peers are other replicas of the same job
inside the cluster, never untrusted parties.

jax arrays are materialized to host numpy on save (``jax.device_get``) and
returned as numpy on load — the consumer decides placement/sharding
(``jax.device_put`` with a NamedSharding) because the healing replica's mesh
layout, not the sender's, governs where shards land.
"""

from __future__ import annotations

import io
import pickle
import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, List, Tuple

import numpy as np

MAGIC = b"TFTC\x01"


def as_byte_view(arr: np.ndarray) -> memoryview:
    """Raw little-endian bytes of a contiguous array; works for extension
    dtypes (bfloat16, fp8) that reject ``memoryview.cast``."""
    return memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16, float8_*) register via ml_dtypes
        import ml_dtypes  # noqa: F401

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class _ArrayPlaceholder:
    index: int
    dtype: str
    shape: Tuple[int, ...]


def shard_key(index: Tuple, shape: Tuple[int, ...]) -> Tuple:
    """Canonical, host-order-independent key for a shard's global index
    (a tuple of resolved ``(start, stop, step)`` per dimension)."""
    key = []
    for dim, sl in enumerate(index):
        if isinstance(sl, slice):
            key.append(sl.indices(shape[dim]))
        else:  # integer index
            key.append((int(sl), int(sl) + 1, 1))
    return tuple(key)


@dataclass
class _ShardedArrayPlaceholder:
    """Skeleton marker for a non-fully-addressable jax Array: this HOST's
    unique shards ride as separate payload arrays keyed by global index."""

    shape: Tuple[int, ...]
    dtype: str
    entries: List[Tuple[Tuple, _ArrayPlaceholder]]


@dataclass
class ShardedHostArray:
    """Host-local deserialized form of a multi-host (non-fully-addressable)
    jax Array: shard data keyed by canonical global index.  Convert back to
    a device array with ``torchft_tpu.ddp.restore_like`` against an existing
    array that carries the target sharding — sender host h and receiver
    host h address identical regions (same mesh + specs across replica
    groups), so the keys match exactly."""

    shape: Tuple[int, ...]
    dtype: str
    shards: dict  # shard_key -> np.ndarray


def _is_array_leaf(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return True
    # jax.Array without importing jax at module import time
    return type(x).__module__.startswith("jax") and hasattr(x, "__array__")


def _is_multihost_jax_array(x: Any) -> bool:
    return (
        type(x).__module__.startswith("jax")
        and hasattr(x, "is_fully_addressable")
        and not x.is_fully_addressable
    )


def _extract_arrays(obj: Any, arrays: List[np.ndarray]) -> Any:
    """Deep-copy the container skeleton, swapping array leaves for
    placeholders (handles dict/list/tuple; other types pickle as-is)."""
    if _is_multihost_jax_array(obj):
        # ship only this host's unique addressable shards; the receiving
        # twin host reassembles them into its identical sharding layout
        shape = tuple(obj.shape)
        unique: dict = {}
        for s in obj.addressable_shards:
            unique.setdefault(shard_key(s.index, shape), s)
        entries: List[Tuple[Tuple, _ArrayPlaceholder]] = []
        for k in sorted(unique):
            arr = np.asarray(unique[k].data)
            entries.append(
                (k, _ArrayPlaceholder(index=len(arrays), dtype=arr.dtype.name, shape=arr.shape))
            )
            arrays.append(arr)
        return _ShardedArrayPlaceholder(
            shape=shape, dtype=obj.dtype.name, entries=entries
        )
    if _is_array_leaf(obj):
        arr = np.asarray(obj)
        # dtype.name (not .str) so extension dtypes like bfloat16 round-trip
        placeholder = _ArrayPlaceholder(
            index=len(arrays), dtype=arr.dtype.name, shape=arr.shape
        )
        arrays.append(arr)
        return placeholder
    if isinstance(obj, dict):
        return {k: _extract_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        mapped = [_extract_arrays(v, arrays) for v in obj]
        if isinstance(obj, list):
            return mapped
        # preserve NamedTuple types (optax optimizer states are namedtuples)
        if hasattr(obj, "_fields"):
            return type(obj)(*mapped)
        return tuple(mapped)
    return obj


def _restore_arrays(obj: Any, arrays: List[np.ndarray]) -> Any:
    if isinstance(obj, _ArrayPlaceholder):
        return arrays[obj.index]
    if isinstance(obj, _ShardedArrayPlaceholder):
        return ShardedHostArray(
            shape=obj.shape,
            dtype=obj.dtype,
            shards={k: arrays[ph.index] for k, ph in obj.entries},
        )
    if isinstance(obj, dict):
        return {k: _restore_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        mapped = [_restore_arrays(v, arrays) for v in obj]
        if isinstance(obj, list):
            return mapped
        if hasattr(obj, "_fields"):
            return type(obj)(*mapped)
        return tuple(mapped)
    return obj


def save_pytree(state: Any, stream: BinaryIO) -> None:
    arrays: List[np.ndarray] = []
    skeleton = _extract_arrays(state, arrays)
    payload = pickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL)

    stream.write(MAGIC)
    stream.write(struct.pack("<I", len(payload)))
    stream.write(payload)
    stream.write(struct.pack("<I", len(arrays)))
    for arr in arrays:
        stream.write(struct.pack("<Q", arr.nbytes))
        stream.write(as_byte_view(arr))


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = stream.read(n - len(out))
        if not chunk:
            raise EOFError("truncated checkpoint stream")
        out += chunk
    return out


def load_pytree(stream: BinaryIO) -> Any:
    magic = _read_exact(stream, len(MAGIC))
    if magic != MAGIC:
        raise ValueError(f"bad checkpoint magic {magic!r}")
    (skel_len,) = struct.unpack("<I", _read_exact(stream, 4))
    skeleton = pickle.loads(_read_exact(stream, skel_len))
    (narrays,) = struct.unpack("<I", _read_exact(stream, 4))

    placeholders: List[_ArrayPlaceholder] = [None] * narrays  # type: ignore[list-item]

    def _collect(obj: Any) -> None:
        if isinstance(obj, _ArrayPlaceholder):
            placeholders[obj.index] = obj
        elif isinstance(obj, _ShardedArrayPlaceholder):
            for _, ph in obj.entries:
                placeholders[ph.index] = ph
        elif isinstance(obj, dict):
            for v in obj.values():
                _collect(v)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                _collect(v)

    _collect(skeleton)

    arrays: List[np.ndarray] = []
    for i in range(narrays):
        ph = placeholders[i]
        assert ph is not None, f"missing placeholder for array {i}"
        (nbytes,) = struct.unpack("<Q", _read_exact(stream, 8))
        dtype = _resolve_dtype(ph.dtype)
        arr = np.empty(ph.shape, dtype=dtype)
        if nbytes != arr.nbytes:
            raise ValueError(
                f"array {i}: payload {nbytes} bytes != expected {arr.nbytes}"
            )
        view = as_byte_view(arr)
        read_into = stream.readinto if hasattr(stream, "readinto") else None
        off = 0
        while off < nbytes:
            if read_into is not None:
                n = read_into(view[off:])
                if not n:
                    raise EOFError("truncated checkpoint stream")
            else:
                chunk = stream.read(min(1 << 20, nbytes - off))
                if not chunk:
                    raise EOFError("truncated checkpoint stream")
                view[off : off + len(chunk)] = chunk
                n = len(chunk)
            off += n
        arrays.append(arr)

    return _restore_arrays(skeleton, arrays)


def dumps_pytree(state: Any) -> bytes:
    buf = io.BytesIO()
    save_pytree(state, buf)
    return buf.getvalue()


def loads_pytree(data: bytes) -> Any:
    return load_pytree(io.BytesIO(data))
