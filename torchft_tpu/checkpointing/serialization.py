"""Streaming serialization for pytrees of jax/numpy arrays.

The reference streams ``torch.save``-serialized state dicts
(``torchft/checkpointing/_serialization.py:14-39``); here the state is an
arbitrary pytree whose array leaves are jax Arrays or numpy arrays.  The
format separates the (pickled) tree skeleton from raw array payloads so
multi-MB tensors stream as straight buffer copies with no pickle overhead:

``TFTC`` magic + version, skeleton (pickle with array leaves replaced by
placeholders), then per-array: dtype tag, shape, raw little-endian bytes.

Like the reference (which pickles tensor metadata over its transports,
``pg_transport.py:32-146``), the skeleton uses pickle and therefore assumes
the same trust model: checkpoint peers are other replicas of the same job
inside the cluster, never untrusted parties.

jax arrays are materialized to host numpy on save (``jax.device_get``) and
returned as numpy on load — the consumer decides placement/sharding
(``jax.device_put`` with a NamedSharding) because the healing replica's mesh
layout, not the sender's, governs where shards land.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, BinaryIO, List, Optional, Tuple

import numpy as np

MAGIC = b"TFTC\x01"

# Target striped-heal chunk size.  Smaller chunks stripe/steal at finer
# granularity (better load balance, cheaper mid-heal failover) at the cost
# of more requests/frames; the default keeps per-chunk overhead <1% on
# multi-MB transfers.
HEAL_CHUNK_MB_ENV = "TORCHFT_HEAL_CHUNK_MB"
DEFAULT_HEAL_CHUNK_BYTES = 4 << 20


def heal_chunk_bytes() -> int:
    mb = os.environ.get(HEAL_CHUNK_MB_ENV)
    if mb:
        return max(1 << 16, int(float(mb) * (1 << 20)))
    return DEFAULT_HEAL_CHUNK_BYTES


def chunk_ranges(
    header_len: int, leaf_nbytes: List[int], target_bytes: int
) -> List[Tuple[int, int]]:
    """Deterministic chunk boundaries over the serialized stream.

    The stream is a sequence of units — the header, then one (8-byte length
    + payload) per array.  Whole units pack greedily up to ``target_bytes``;
    a unit larger than the target splits at target granularity from its own
    start.  Boundaries are therefore a pure function of the tree structure
    and leaf sizes, so every peer holding the same state at the same step
    produces the SAME ranges over byte-identical content — the property that
    lets a healer assemble one buffer from many peers' streams.
    """
    target = max(1, int(target_bytes))
    units = [header_len] + [8 + n for n in leaf_nbytes]
    chunks: List[Tuple[int, int]] = []
    off = 0
    cur_start = 0
    cur = 0  # bytes accumulated in the open chunk
    for unit in units:
        if unit > target:
            if cur:
                chunks.append((cur_start, off))
            start = off
            while start < off + unit:
                stop = min(off + unit, start + target)
                chunks.append((start, stop))
                start = stop
            off += unit
            cur_start, cur = off, 0
            continue
        off += unit
        cur += unit
        if cur >= target:
            chunks.append((cur_start, off))
            cur_start, cur = off, 0
    if cur:
        chunks.append((cur_start, off))
    return chunks


def as_byte_view(arr: np.ndarray) -> memoryview:
    """Raw little-endian bytes of a contiguous array; works for extension
    dtypes (bfloat16, fp8) that reject ``memoryview.cast``."""
    return memoryview(np.ascontiguousarray(arr).reshape(-1).view(np.uint8))


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16, float8_*) register via ml_dtypes
        import ml_dtypes  # noqa: F401

        return np.dtype(getattr(ml_dtypes, name))


@dataclass
class _ArrayPlaceholder:
    index: int
    dtype: str
    shape: Tuple[int, ...]


def shard_key(index: Tuple, shape: Tuple[int, ...]) -> Tuple:
    """Canonical, host-order-independent key for a shard's global index
    (a tuple of resolved ``(start, stop, step)`` per dimension)."""
    key = []
    for dim, sl in enumerate(index):
        if isinstance(sl, slice):
            key.append(sl.indices(shape[dim]))
        else:  # integer index
            key.append((int(sl), int(sl) + 1, 1))
    return tuple(key)


@dataclass
class _ShardedArrayPlaceholder:
    """Skeleton marker for a non-fully-addressable jax Array: this HOST's
    unique shards ride as separate payload arrays keyed by global index."""

    shape: Tuple[int, ...]
    dtype: str
    entries: List[Tuple[Tuple, _ArrayPlaceholder]]


@dataclass
class ShardedHostArray:
    """Host-local deserialized form of a multi-host (non-fully-addressable)
    jax Array: shard data keyed by canonical global index.  Convert back to
    a device array with ``torchft_tpu.ddp.restore_like`` against an existing
    array that carries the target sharding — sender host h and receiver
    host h address identical regions (same mesh + specs across replica
    groups), so the keys match exactly."""

    shape: Tuple[int, ...]
    dtype: str
    shards: dict  # shard_key -> np.ndarray


def _is_array_leaf(x: Any) -> bool:
    if isinstance(x, np.ndarray):
        return True
    # jax.Array without importing jax at module import time
    return type(x).__module__.startswith("jax") and hasattr(x, "__array__")


def _is_multihost_jax_array(x: Any) -> bool:
    return (
        type(x).__module__.startswith("jax")
        and hasattr(x, "is_fully_addressable")
        and not x.is_fully_addressable
    )


def _is_shard(leaf: Any) -> bool:
    """jax Shard: carries its array in ``.data`` and is not itself
    array-like.  The ``__array__`` check must come FIRST: probing ``.data``
    on a numpy extension-dtype array (e.g. ml_dtypes bfloat16, as produced
    by ``np.asarray`` of a bf16 jax array — DiLoCo fragment backups) raises
    ValueError out of ``hasattr``, since buffers cannot carry dtype 'E'."""
    return not hasattr(leaf, "__array__") and hasattr(leaf, "data")


def materialize_leaf(leaf: Any) -> np.ndarray:
    """Host numpy view/copy of a collected leaf (jax arrays device_get
    here, NOT at extraction time — the point of the lazy plan is that only
    one leaf's host copy is ever live during a streaming send)."""
    if isinstance(leaf, np.ndarray):
        return leaf
    if _is_shard(leaf):
        return np.asarray(leaf.data)
    return np.asarray(leaf)


def _leaf_meta(leaf: Any) -> Tuple[str, Tuple[int, ...]]:
    """(dtype name, shape) without materializing the leaf on host."""
    if _is_shard(leaf):
        leaf = leaf.data
    return np.dtype(leaf.dtype).name, tuple(leaf.shape)


def _extract_arrays(obj: Any, arrays: List[Any]) -> Any:
    """Deep-copy the container skeleton, swapping array leaves for
    placeholders (handles dict/list/tuple; other types pickle as-is).

    ``arrays`` collects the RAW leaves (numpy arrays, jax Arrays, jax
    Shards) — call :func:`materialize_leaf` to get host bytes for one."""
    if _is_multihost_jax_array(obj):
        # ship only this host's unique addressable shards; the receiving
        # twin host reassembles them into its identical sharding layout
        shape = tuple(obj.shape)
        unique: dict = {}
        for s in obj.addressable_shards:
            unique.setdefault(shard_key(s.index, shape), s)
        entries: List[Tuple[Tuple, _ArrayPlaceholder]] = []
        for k in sorted(unique):
            dtype_name, sshape = _leaf_meta(unique[k])
            entries.append(
                (k, _ArrayPlaceholder(index=len(arrays), dtype=dtype_name, shape=sshape))
            )
            arrays.append(unique[k])
        return _ShardedArrayPlaceholder(
            shape=shape, dtype=obj.dtype.name, entries=entries
        )
    if _is_array_leaf(obj):
        # dtype.name (not .str) so extension dtypes like bfloat16 round-trip
        dtype_name, shape = _leaf_meta(obj)
        placeholder = _ArrayPlaceholder(
            index=len(arrays), dtype=dtype_name, shape=shape
        )
        arrays.append(obj)
        return placeholder
    if isinstance(obj, dict):
        return {k: _extract_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        mapped = [_extract_arrays(v, arrays) for v in obj]
        if isinstance(obj, list):
            return mapped
        # preserve NamedTuple types (optax optimizer states are namedtuples)
        if hasattr(obj, "_fields"):
            return type(obj)(*mapped)
        return tuple(mapped)
    return obj


def _restore_arrays(obj: Any, arrays: List[np.ndarray]) -> Any:
    if isinstance(obj, _ArrayPlaceholder):
        return arrays[obj.index]
    if isinstance(obj, _ShardedArrayPlaceholder):
        return ShardedHostArray(
            shape=obj.shape,
            dtype=obj.dtype,
            shards={k: arrays[ph.index] for k, ph in obj.entries},
        )
    if isinstance(obj, dict):
        return {k: _restore_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        mapped = [_restore_arrays(v, arrays) for v in obj]
        if isinstance(obj, list):
            return mapped
        if hasattr(obj, "_fields"):
            return type(obj)(*mapped)
        return tuple(mapped)
    return obj


@dataclass
class PytreePlan:
    """Serialization plan: everything needed to stream a pytree without
    materializing more than one leaf on host at a time.

    ``header`` is the byte prefix (magic + skeleton + array count); each
    leaf then rides as an 8-byte length + raw bytes.  ``total_len`` lets a
    server send Content-Length before generating a byte of payload."""

    header: bytes
    leaves: List[Any]
    leaf_nbytes: List[int]
    total_len: int
    # one-leaf D2H memo: several striped range requests cut the same large
    # leaf, and each write_range would otherwise device_get the whole leaf
    # again; the memo holds the most recent materialization
    _memo: Optional[Tuple[int, np.ndarray]] = None
    _memo_lock: threading.Lock = field(default_factory=threading.Lock)

    def header_digest(self) -> str:
        """Digest of the byte prefix (magic + skeleton + count).  Striped
        healers compare it across sources: peers serving the same step must
        agree byte-for-byte or assembling one buffer from many streams would
        silently corrupt."""
        return hashlib.sha256(self.header).hexdigest()

    def chunk_ranges(
        self, target_bytes: Optional[int] = None
    ) -> List[Tuple[int, int]]:
        return chunk_ranges(
            len(self.header), self.leaf_nbytes, target_bytes or heal_chunk_bytes()
        )

    def _materialize(self, index: int) -> np.ndarray:
        with self._memo_lock:
            if self._memo is not None and self._memo[0] == index:
                return self._memo[1]
        arr = materialize_leaf(self.leaves[index])
        with self._memo_lock:
            self._memo = (index, arr)
        return arr

    def write_range(self, start: int, stop: int, stream: BinaryIO) -> None:
        """Stream bytes [start, stop) of the serialized form, materializing
        only the leaves that overlap the range (chunked HTTP fetches)."""
        off = 0

        def _emit(chunk) -> None:
            nonlocal off
            n = len(chunk)
            lo, hi = max(start, off), min(stop, off + n)
            if lo < hi:
                stream.write(memoryview(chunk)[lo - off : hi - off])
            off += n

        _emit(self.header)
        for i, nbytes in enumerate(self.leaf_nbytes):
            if off + 8 + nbytes <= start:
                off += 8 + nbytes  # fully before the range: skip cheaply
                continue
            if off >= stop:
                break
            _emit(struct.pack("<Q", nbytes))
            if off + nbytes <= start:
                off += nbytes
                continue
            _emit(as_byte_view(self._materialize(i)))


def _snapshot_leaf(leaf: Any) -> Any:
    """Point-in-time snapshot of one collected leaf without bringing it to
    host: numpy copies on host (the train loop may mutate it in place, e.g.
    LocalSGD host params); jax arrays/shards copy ON DEVICE (HBM-to-HBM) —
    a mere reference would die when a donating jit (HSDPTrainer's update,
    ``parallel/hsdp.py``) consumes the original buffer mid-stream."""
    if isinstance(leaf, np.ndarray):
        return leaf.copy()
    import jax.numpy as jnp

    if _is_shard(leaf):
        return jnp.copy(leaf.data)  # jax Shard -> single-device array copy
    return jnp.copy(leaf)


def plan_pytree(state: Any, snapshot: bool = False) -> PytreePlan:
    """Build the streaming plan for ``state``.

    ``snapshot`` makes the plan a point-in-time checkpoint that stays valid
    while training continues: numpy leaves are host-copied, jax leaves are
    device-copied (see :func:`_snapshot_leaf`); host bytes still materialize
    one leaf at a time during streaming."""
    arrays: List[Any] = []
    skeleton = _extract_arrays(state, arrays)
    if snapshot:
        arrays = [_snapshot_leaf(a) for a in arrays]
    payload = pickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL)
    header = (
        MAGIC
        + struct.pack("<I", len(payload))
        + payload
        + struct.pack("<I", len(arrays))
    )
    leaf_nbytes = []
    for leaf in arrays:
        dtype_name, shape = _leaf_meta(leaf)
        nbytes = _resolve_dtype(dtype_name).itemsize
        for d in shape:
            nbytes *= d
        leaf_nbytes.append(nbytes)
    total = len(header) + sum(8 + n for n in leaf_nbytes)
    return PytreePlan(
        header=header, leaves=arrays, leaf_nbytes=leaf_nbytes, total_len=total
    )


def save_pytree(state: Any, stream: BinaryIO) -> None:
    """Stream-serialize: leaves are materialized to host one at a time as
    they are written (peak extra host RSS ≈ one leaf)."""
    plan = plan_pytree(state)
    stream.write(plan.header)
    for leaf, nbytes in zip(plan.leaves, plan.leaf_nbytes):
        arr = materialize_leaf(leaf)
        assert arr.nbytes == nbytes, (arr.nbytes, nbytes)
        stream.write(struct.pack("<Q", nbytes))
        stream.write(as_byte_view(arr))


def _read_exact(stream: BinaryIO, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = stream.read(n - len(out))
        if not chunk:
            raise EOFError("truncated checkpoint stream")
        out += chunk
    return out


def load_pytree(stream: BinaryIO, leaf_hook: Any = None) -> Any:
    """Inverse of :func:`save_pytree`, reading payloads straight into
    preallocated arrays (``readinto``, no intermediate copies).

    ``leaf_hook(arr) -> Any``, if given, maps each array right after its
    bytes arrive — e.g. ``jax.device_put`` with the healing replica's target
    sharding — so the host copy of each leaf can be dropped as soon as the
    next one starts arriving (in-place-on-arrival heal)."""
    magic = _read_exact(stream, len(MAGIC))
    if magic != MAGIC:
        raise ValueError(f"bad checkpoint magic {magic!r}")
    (skel_len,) = struct.unpack("<I", _read_exact(stream, 4))
    skeleton = pickle.loads(_read_exact(stream, skel_len))
    (narrays,) = struct.unpack("<I", _read_exact(stream, 4))

    placeholders: List[_ArrayPlaceholder] = [None] * narrays  # type: ignore[list-item]

    def _collect(obj: Any) -> None:
        if isinstance(obj, _ArrayPlaceholder):
            placeholders[obj.index] = obj
        elif isinstance(obj, _ShardedArrayPlaceholder):
            for _, ph in obj.entries:
                placeholders[ph.index] = ph
        elif isinstance(obj, dict):
            for v in obj.values():
                _collect(v)
        elif isinstance(obj, (list, tuple)):
            for v in obj:
                _collect(v)

    _collect(skeleton)

    arrays: List[np.ndarray] = []
    for i in range(narrays):
        ph = placeholders[i]
        assert ph is not None, f"missing placeholder for array {i}"
        (nbytes,) = struct.unpack("<Q", _read_exact(stream, 8))
        dtype = _resolve_dtype(ph.dtype)
        arr = np.empty(ph.shape, dtype=dtype)
        if nbytes != arr.nbytes:
            raise ValueError(
                f"array {i}: payload {nbytes} bytes != expected {arr.nbytes}"
            )
        view = as_byte_view(arr)
        read_into = stream.readinto if hasattr(stream, "readinto") else None
        off = 0
        while off < nbytes:
            if read_into is not None:
                n = read_into(view[off:])
                if not n:
                    raise EOFError("truncated checkpoint stream")
            else:
                chunk = stream.read(min(1 << 20, nbytes - off))
                if not chunk:
                    raise EOFError("truncated checkpoint stream")
                view[off : off + len(chunk)] = chunk
                n = len(chunk)
            off += n
        arrays.append(arr if leaf_hook is None else leaf_hook(arr))

    return _restore_arrays(skeleton, arrays)


def array_chunk_ranges(
    nbytes_list: List[int], target_bytes: int
) -> List[Tuple[int, int, int]]:
    """Chunk index at RAW array-payload granularity: ``(array_index, start,
    stop)`` byte ranges within each array's buffer, each at most
    ``target_bytes`` long.  Used by the comm-transport striped heal, whose
    chunks land directly in the final (preallocated) array buffers — no
    serialized-stream reassembly pass.  Deterministic given identical array
    metas, which same-step peers share by construction."""
    target = max(1, int(target_bytes))
    out: List[Tuple[int, int, int]] = []
    for ai, n in enumerate(nbytes_list):
        start = 0
        while start < n:
            stop = min(n, start + target)
            out.append((ai, start, stop))
            start = stop
    return out


def balanced_shares(sizes: List[int], num_shares: int) -> List[List[int]]:
    """Deterministic byte-balanced assignment of chunk indices to shares
    (greedy longest-first onto the least-loaded share, ties to the lowest
    index).  Plain ``idx % num_shares`` can hand one source most of the
    bytes when chunk sizes are uneven — the heal then runs at the slowest
    share's pace.  Every peer computes the SAME assignment from the same
    chunk table, which is what lets senders and the healer agree without a
    negotiation round-trip."""
    order = sorted(range(len(sizes)), key=lambda i: (-sizes[i], i))
    loads = [0] * num_shares
    shares: List[List[int]] = [[] for _ in range(num_shares)]
    for i in order:
        target = min(range(num_shares), key=lambda s: (loads[s], s))
        shares[target].append(i)
        loads[target] += sizes[i]
    return [sorted(s) for s in shares]


class ViewReader:
    """Minimal read/readinto stream over a memoryview (no BytesIO copy) —
    the zero-copy way to ``load_pytree`` an assembled striped-heal buffer."""

    def __init__(self, view: memoryview) -> None:
        self._view = view
        self._off = 0

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = len(self._view) - self._off
        out = bytes(self._view[self._off : self._off + n])
        self._off += len(out)
        return out

    def readinto(self, out) -> int:
        n = min(len(out), len(self._view) - self._off)
        out[:n] = self._view[self._off : self._off + n]
        self._off += n
        return n


def dumps_pytree(state: Any) -> bytes:
    buf = io.BytesIO()
    save_pytree(state, buf)
    return buf.getvalue()


def loads_pytree(data: bytes) -> Any:
    return load_pytree(io.BytesIO(data))
