"""Replica-group launcher: run an FT job on one or many hosts.

The reference ships a TorchX component that launches N single-node torchrun
roles with ``REPLICA_GROUP_ID`` / ``NUM_REPLICA_GROUPS`` env plumbing
(``torchft/torchx.py:17-89``) plus a SLURM runner
(``torchft/examples/slurm/runner.py``).  torchft_tpu's launcher does the
same job for TPU-VM style deployments: spawn one training process per
replica group, each pointed at the shared lighthouse, with automatic restart
of crashed groups (the scheduler role the reference delegates to
torchx/SLURM/Monarch).

CLI::

    python -m torchft_tpu.launcher --replicas 2 --min-replicas 1 \
        -- python examples/train_ddp.py --steps 100

Env contract for the child (same names as the reference):
``TORCHFT_LIGHTHOUSE``, ``REPLICA_GROUP_ID``, ``NUM_REPLICA_GROUPS``.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger("torchft_tpu.launcher")


def _reap_async(proc: subprocess.Popen, what: str) -> Optional[threading.Thread]:
    """Wait → SIGKILL → wait, off-thread.  The caller delivers SIGTERM
    inline FIRST — off-thread delivery could be skipped entirely if the
    supervisor exits before the daemon thread runs.

    Retirement runs on the supervisor's poll loop; blocking it for a wedged
    child (SIGTERM ignored in native code) would stall crash detection for
    every OTHER group, so escalation happens on a daemon reaper thread.
    ``Popen.wait`` is safe to call concurrently (internal waitpid lock).
    Returns the reaper thread so terminal paths (``stop()``/``run()``) can
    join it — daemon threads die with the interpreter, which would skip the
    SIGKILL."""
    if proc.poll() is not None:
        return None

    def _reap() -> None:
        try:
            proc.wait(timeout=5.0)
            return
        except subprocess.TimeoutExpired:
            pass
        proc.kill()
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:  # pragma: no cover
            logger.warning("%s did not die after SIGKILL", what)

    t = threading.Thread(target=_reap, name=f"reap-{what}", daemon=True)
    t.start()
    return t


@dataclass
class ReplicaSpec:
    replica_group_id: int
    cmd: List[str]
    env: Dict[str, str] = field(default_factory=dict)
    # when set, the group's stdout/stderr append here (survives restarts)
    log_path: Optional[str] = None
    # warm standby: keep a pre-initialized spare process parked behind the
    # active one and promote it on death (see ReplicaSupervisor)
    standby: bool = False


STANDBY_GATE_ENV = "TPUFT_STANDBY_GATE"


class ReplicaSupervisor:
    """Spawn + monitor + restart replica-group processes.

    ``max_restarts`` bounds per-group restarts (None = unlimited), matching
    the respawn loop of the reference's SLURM/Monarch orchestrators.

    **Warm standby** (``ReplicaSpec.standby=True``): alongside the active
    process, a spare runs the same command with ``TPUFT_STANDBY_GATE=<file>``
    in its env.  A standby-aware worker does all its expensive
    initialization (python boot, jax/TPU backend dial, model build,
    compilation) and then parks, polling for the gate file; it must NOT
    join the quorum while parked.  When the active process dies, the
    supervisor *promotes* the standby by creating its gate file — the spare
    joins the quorum and heals within a step or two instead of paying tens
    of seconds of cold start — and pre-warms a fresh standby behind it.
    This is the process-level analog of the reference's quorum-level spares
    (``WorldSizeMode.FIXED_WITH_SPARES``, ``torchft/manager.py:123-139``).
    Workers that ignore the env var simply run twice, so only enable it for
    standby-aware commands.
    """

    def __init__(
        self,
        specs: List[ReplicaSpec],
        lighthouse_addr: str,
        max_restarts: Optional[int] = None,
        restart_delay_s: float = 1.0,
    ) -> None:
        self._specs = specs
        self._lighthouse_addr = lighthouse_addr
        self._max_restarts = max_restarts
        self._restart_delay_s = restart_delay_s
        self._procs: Dict[int, subprocess.Popen] = {}
        self._standbys: Dict[int, Tuple[subprocess.Popen, str]] = {}
        self._restarts: Dict[int, int] = {}
        self._gate_dir: Optional[str] = None
        self._gate_seq = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._reapers: List[threading.Thread] = []

    def _spawn(
        self, spec: ReplicaSpec, standby_gate: Optional[str] = None
    ) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(spec.env)
        env["TORCHFT_LIGHTHOUSE"] = self._lighthouse_addr
        env["REPLICA_GROUP_ID"] = str(spec.replica_group_id)
        env["NUM_REPLICA_GROUPS"] = str(len(self._specs))
        if standby_gate is not None:
            env[STANDBY_GATE_ENV] = standby_gate
        else:
            env.pop(STANDBY_GATE_ENV, None)
        logger.info(
            "launching replica group %d: %s", spec.replica_group_id, spec.cmd
        )
        log = None
        if spec.log_path:
            try:
                log = open(spec.log_path, "ab")
            except OSError as e:
                # a broken log sink (deleted dir, full disk) must not take
                # down supervision of every other group — run unlogged
                logger.warning(
                    "replica group %d: cannot open log %s (%s); running unlogged",
                    spec.replica_group_id,
                    spec.log_path,
                    e,
                )
        try:
            if log is not None:
                return subprocess.Popen(
                    spec.cmd, env=env, stdout=log, stderr=subprocess.STDOUT
                )
            return subprocess.Popen(spec.cmd, env=env)
        finally:
            if log is not None:
                log.close()  # the child holds its own fd

    def _new_standby(self, spec: ReplicaSpec) -> Tuple[subprocess.Popen, str]:
        if self._gate_dir is None:
            self._gate_dir = tempfile.mkdtemp(prefix="tpuft_standby_")
        self._gate_seq += 1
        gate = os.path.join(
            self._gate_dir,
            f"gate_{spec.replica_group_id}_{self._gate_seq}",
        )
        return self._spawn(spec, standby_gate=gate), gate

    def run(self) -> int:
        """Run until every group exits cleanly (rc 0) or is out of restarts.
        Returns the worst exit code."""
        with self._lock:
            # _stop re-checked under the lock (same race class as the
            # respawn/re-warm paths): a stop() that ran before this spawn
            # loop snapshotted an empty fleet and will terminate nothing
            for spec in self._specs:
                if self._stop.is_set():
                    break
                self._procs[spec.replica_group_id] = self._spawn(spec)
                self._restarts[spec.replica_group_id] = 0
                if spec.standby:
                    self._standbys[spec.replica_group_id] = self._new_standby(
                        spec
                    )

        worst_rc = 0
        alive = {spec.replica_group_id for spec in self._specs}
        try:
            worst_rc = self._supervise(alive)
        finally:
            # always — an exception escaping the supervise loop must not
            # abandon retire-path reapers mid-escalation (daemon threads
            # die with the interpreter, skipping SIGTERM/SIGKILL)
            self._drain_reapers()
        return worst_rc

    def _supervise(self, alive: set) -> int:
        worst_rc = 0
        while alive and not self._stop.is_set():
            time.sleep(0.2)
            for spec in self._specs:
                gid = spec.replica_group_id
                if gid not in alive:
                    continue
                # a standby that died while parked is replaced quietly (it
                # was never part of the fleet)
                if spec.standby:
                    with self._lock:
                        sb = self._standbys.get(gid)
                        # re-check under the lock: a re-warm racing stop()
                        # would land a fresh spare AFTER stop() cleared
                        # _standbys — never terminated, outliving the
                        # supervisor
                        if (
                            sb is not None
                            and sb[0].poll() is not None
                            and not self._stop.is_set()
                        ):
                            logger.warning(
                                "standby for group %d died while parked; "
                                "re-warming",
                                gid,
                            )
                            self._standbys[gid] = self._new_standby(spec)
                proc = self._procs[gid]
                rc = proc.poll()
                if rc is None:
                    continue
                if rc == 0:
                    logger.info("replica group %d finished", gid)
                    alive.discard(gid)
                    self._retire_standby(gid)
                    continue
                # crash: restart (the whole point of per-step fault tolerance
                # is that the surviving groups kept training meanwhile)
                self._restarts[gid] += 1
                if (
                    self._max_restarts is not None
                    and self._restarts[gid] > self._max_restarts
                ):
                    logger.error(
                        "replica group %d exceeded max_restarts (%d), giving up",
                        gid,
                        self._max_restarts,
                    )
                    # poll() reports signal deaths as negative; a permanently
                    # failed group must never read as success
                    worst_rc = max(worst_rc, abs(rc) or 1)
                    alive.discard(gid)
                    self._retire_standby(gid)
                    continue
                promoted = False
                with self._lock:
                    sb = self._standbys.pop(gid, None)
                    if sb is not None and sb[0].poll() is None:
                        # promote: the gate file releases the parked spare,
                        # which joins the quorum already warm — no restart
                        # delay, no cold start
                        with open(sb[1], "w"):
                            pass
                        self._procs[gid] = sb[0]
                        promoted = True
                if promoted:
                    logger.warning(
                        "replica group %d exited rc=%d; promoted warm "
                        "standby (%d)",
                        gid,
                        rc,
                        self._restarts[gid],
                    )
                    with self._lock:
                        if spec.standby and not self._stop.is_set():
                            self._standbys[gid] = self._new_standby(spec)
                    continue
                logger.warning(
                    "replica group %d exited rc=%d; restarting (%d)",
                    gid,
                    rc,
                    self._restarts[gid],
                )
                time.sleep(self._restart_delay_s)
                if self._stop.is_set():
                    break
                with self._lock:
                    # under the lock, re-checking _stop: stop() sets the
                    # flag before snapshotting under this same lock, so a
                    # respawn racing it would land a child stop() never
                    # terminates
                    if self._stop.is_set():
                        break
                    self._procs[gid] = self._spawn(spec)
        return worst_rc

    # bounded by _reap_async's 5 s SIGTERM + 5 s SIGKILL waits, plus margin
    _REAP_DEADLINE_S = 12.0

    def _drain_reapers(self, extra: Sequence[threading.Thread] = ()) -> None:
        """Join all outstanding reaper threads (terminal paths only):
        daemon reapers die with the interpreter, which would skip the
        SIGKILL escalation for a child wedged in native code."""
        with self._lock:
            reapers, self._reapers = self._reapers + list(extra), []
        deadline = time.monotonic() + self._REAP_DEADLINE_S
        for t in reapers:
            t.join(timeout=max(0.0, deadline - time.monotonic()))

    def _retire_standby(self, replica_group_id: int) -> None:
        """A group that left the fleet (clean exit or out of restarts) must
        not leak its parked spare — the spare holds TPU/compile resources."""
        with self._lock:
            sb = self._standbys.pop(replica_group_id, None)
        if sb is not None:
            # SIGTERM inline (the reaper thread only escalates): if the
            # supervisor exits before the daemon reaper runs, the spare must
            # at least have been told to die
            if sb[0].poll() is None:
                sb[0].terminate()
            t = _reap_async(sb[0], f"standby for group {replica_group_id}")
            if t is not None:
                # terminal paths (stop / run-exit) join these: a daemon
                # reaper dying with the interpreter would skip the SIGKILL
                with self._lock:
                    self._reapers.append(t)

    def kill(self, replica_group_id: int, sig: int = signal.SIGKILL) -> bool:
        """Chaos hook: kill one group's process (it will be restarted)."""
        with self._lock:
            proc = self._procs.get(replica_group_id)
        if proc is None or proc.poll() is not None:
            return False
        proc.send_signal(sig)
        return True

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            procs = list(self._procs.values())
            standbys = [p for p, _gate in self._standbys.values()]
            self._standbys.clear()
        # SIGTERM is delivered inline — stop() may be the supervisor's last
        # act, and a daemon reaper thread is not guaranteed to run before
        # interpreter exit.  The wait/SIGKILL escalation runs on reaper
        # threads (concurrently across children) but stop() JOINS them with
        # a bounded deadline: primaries and spares alike must not outlive
        # the supervisor holding TPU resources, even when wedged in native
        # code ignoring SIGTERM.
        reapers = []
        for proc in procs + standbys:
            if proc.poll() is None:
                proc.terminate()
                t = _reap_async(proc, "child (supervisor stop)")
                if t is not None:
                    reapers.append(t)
        self._drain_reapers(extra=reapers)


def main(argv: Optional[List[str]] = None) -> None:
    parser = argparse.ArgumentParser(
        "torchft_tpu.launcher",
        description="Launch N fault-tolerant replica groups + a lighthouse.",
    )
    parser.add_argument("--replicas", type=int, required=True)
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument(
        "--lighthouse",
        default=None,
        help="existing lighthouse addr; if unset, one is started in-process",
    )
    parser.add_argument("--join-timeout-ms", type=int, default=60_000)
    parser.add_argument("--max-restarts", type=int, default=None)
    parser.add_argument(
        "--native",
        action="store_true",
        help="serve the lighthouse from the C++ runtime",
    )
    parser.add_argument("cmd", nargs=argparse.REMAINDER, help="-- <training cmd>")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        parser.error("training command required after --")

    lighthouse = None
    lighthouse_addr = args.lighthouse
    if lighthouse_addr is None:
        if args.native:
            from torchft_tpu.native import CppLighthouseServer

            lighthouse = CppLighthouseServer(
                bind="0.0.0.0:0",
                min_replicas=args.min_replicas,
                join_timeout_ms=args.join_timeout_ms,
            )
        else:
            from torchft_tpu.lighthouse import LighthouseServer

            lighthouse = LighthouseServer(
                bind="0.0.0.0:0",
                min_replicas=args.min_replicas,
                join_timeout_ms=args.join_timeout_ms,
            )
        lighthouse_addr = f"127.0.0.1:{lighthouse.port}"
        logger.info("started lighthouse on %s", lighthouse_addr)

    specs = [ReplicaSpec(replica_group_id=i, cmd=list(cmd)) for i in range(args.replicas)]
    supervisor = ReplicaSupervisor(
        specs, lighthouse_addr, max_restarts=args.max_restarts
    )
    try:
        rc = supervisor.run()
    except KeyboardInterrupt:
        supervisor.stop()
        rc = 130
    finally:
        if lighthouse is not None:
            lighthouse.shutdown()
    sys.exit(rc)


if __name__ == "__main__":
    main()
