"""Userspace timeout engine.

The reference routes every async op through a singleton ``_TimeoutManager``
(background asyncio loop + watchdog thread, ``torchft/futures.py:50-277``) so
that collective timeouts are *userspace and per-operation, never
process-fatal* (SURVEY.md §5.8 requirement 5).  torchft_tpu keeps the same
doctrine with a single deadline-servicing thread: ops register a deadline and
a callback (typically ``communicator.abort``); firing the callback unblocks
the wedged op, which then surfaces as a recorded error, not a crash.

A watchdog guards the timer thread itself: if the timer thread stops
servicing deadlines (the analog of the reference's wedged event loop,
``torchft/futures.py:102-125``) the watchdog hard-exits the process so the
scheduler can reschedule the replica.  Controlled by
``TORCHFT_WATCHDOG_TIMEOUT_SEC`` (0 disables; disabled by default under
pytest).
"""

from __future__ import annotations

import heapq
import itertools
import logging
import os
import sys
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

WATCHDOG_TIMEOUT_SEC_ENV = "TORCHFT_WATCHDOG_TIMEOUT_SEC"


class TimerHandle:
    __slots__ = ("_cancelled", "_fired")

    def __init__(self) -> None:
        self._cancelled = False
        self._fired = False

    def cancel(self) -> None:
        self._cancelled = True

    @property
    def fired(self) -> bool:
        return self._fired


class _TimerThread:
    """Single background thread servicing monotonic deadlines."""

    def __init__(self) -> None:
        self._heap: list[tuple[float, int, TimerHandle, Callable[[], None]]] = []
        self._counter = itertools.count()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._last_tick = time.monotonic()
        self._watchdog: Optional[threading.Thread] = None

    def _ensure_started(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="tpuft_timers", daemon=True
        )
        self._thread.start()
        watchdog_sec = float(os.environ.get(WATCHDOG_TIMEOUT_SEC_ENV, "0") or 0)
        if watchdog_sec > 0 and self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._run_watchdog,
                args=(watchdog_sec,),
                name="tpuft_watchdog",
                daemon=True,
            )
            self._watchdog.start()

    def schedule(self, delay_s: float, callback: Callable[[], None]) -> TimerHandle:
        handle = TimerHandle()
        deadline = time.monotonic() + delay_s
        with self._cond:
            self._ensure_started()
            heapq.heappush(self._heap, (deadline, next(self._counter), handle, callback))
            self._cond.notify()
        return handle

    def _run(self) -> None:
        while True:
            with self._cond:
                self._last_tick = time.monotonic()
                while not self._heap:
                    self._cond.wait(timeout=1.0)
                    self._last_tick = time.monotonic()
                deadline, _, handle, callback = self._heap[0]
                now = time.monotonic()
                if deadline > now:
                    self._cond.wait(timeout=min(deadline - now, 1.0))
                    continue
                heapq.heappop(self._heap)
            if handle._cancelled:
                continue
            handle._fired = True
            try:
                callback()
            except Exception:  # noqa: BLE001
                logger.exception("timer callback raised")

    def _run_watchdog(self, timeout_s: float) -> None:
        while True:
            time.sleep(timeout_s / 2)
            with self._cond:
                stalled = (
                    bool(self._heap)
                    and time.monotonic() - self._last_tick > timeout_s
                )
            if stalled:
                logger.error(
                    "timer thread wedged for >%ss; exiting so the scheduler can "
                    "restart this replica",
                    timeout_s,
                )
                sys.stdout.flush()
                sys.stderr.flush()
                os._exit(1)


_TIMERS = _TimerThread()


def schedule_timeout(delay_s: float, callback: Callable[[], None]) -> TimerHandle:
    """Run ``callback`` after ``delay_s`` unless cancelled first."""
    return _TIMERS.schedule(delay_s, callback)


def future_timeout(fut: "Future[Any]", timeout_s: float) -> "Future[Any]":
    """Return a future that mirrors ``fut`` but fails with ``TimeoutError``
    after ``timeout_s`` (``torchft/futures.py:280-292``)."""
    out: Future[Any] = Future()

    def _on_timeout() -> None:
        if out.done():
            return  # lost the race against fut completing; benign
        try:
            out.set_exception(TimeoutError(f"future timed out after {timeout_s}s"))
        except Exception:  # noqa: BLE001 - resolved between check and set
            pass

    handle = schedule_timeout(timeout_s, _on_timeout)

    def _chain(f: "Future[Any]") -> None:
        handle.cancel()
        if out.done():
            return
        try:
            if f.cancelled():
                out.cancel()
                # cancel() on an un-started Future resolves it; if something
                # already set it running, surface cancellation as an error
                if not out.done():
                    out.set_exception(TimeoutError("source future was cancelled"))
                return
            err = f.exception()
            if err is not None:
                out.set_exception(err)
            else:
                out.set_result(f.result())
        except Exception:  # noqa: BLE001 - future already resolved by timeout
            pass

    fut.add_done_callback(_chain)
    return out


def future_wait(fut: "Future[Any]", timeout_s: float) -> Any:
    """Block on ``fut`` with a deadline (``torchft/futures.py:295-322``)."""
    return fut.result(timeout=timeout_s)


class context_timeout:
    """``with context_timeout(cb, t):`` — arm ``cb`` unless the body finishes
    within ``t`` seconds (``torchft/futures.py:340-354``)."""

    def __init__(self, callback: Callable[[], None], timeout_s: float) -> None:
        self._callback = callback
        self._timeout_s = timeout_s
        self._handle: Optional[TimerHandle] = None

    def __enter__(self) -> "context_timeout":
        self._handle = schedule_timeout(self._timeout_s, self._callback)
        return self

    def __exit__(self, *exc: object) -> None:
        assert self._handle is not None
        self._handle.cancel()
