"""Thread-safety checker: unlocked read-modify-write of cross-thread state.

Per class, the checker builds a *thread-entry graph*:

- entry points are every ``threading.Thread(target=self.X)`` spawn and
  every executor ``submit(self.X, ...)`` (lambdas passed to either
  contribute the ``self`` methods they call) — RPC handler methods are
  covered transitively, because the accept loop that dispatches them is
  itself a ``Thread`` target;
- the implicit *caller* context covers the public surface (public methods,
  ``__init__``/dunders) and everything they reach via ``self.*()`` calls;
- each entry's transitive ``self.*()`` call closure defines which methods
  run in which context.

An attribute of ``self`` that is *mutated* from two or more distinct
contexts is shared mutable state; every mutation site of it that is a
**read-modify-write** (``+=`` / ``x = f(x)`` / container mutation /
item assignment) and not lexically under a ``with <lock>`` is flagged —
exactly the non-atomic ``_inflight_ops +=`` class the PR-6 review caught
by hand.

Escape hatches the checker understands (document WHY at the use site):

- ``with self._lock:`` (any name containing ``lock``/``mutex``, or the
  ``_mu``/``_cv``/``_cond`` suffixes — condition variables are locks);
- a method whose name ends in ``_locked`` asserts its callers hold the
  lock, so its sites are treated as locked;
- ``# ftlint: ignore[thread-safety] — <reason>`` on the site's line.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from torchft_tpu.analysis.core import Finding, iter_py_files

CHECKER = "thread-safety"

# Method calls that mutate a builtin container in place.  Each is
# individually GIL-atomic on builtins, but paired with ANY other access
# from a second thread they form the check-then-act races this checker
# exists for (and on non-builtin types not even the single call is safe).
_CONTAINER_MUTATORS = frozenset(
    {
        "append", "extend", "insert", "remove", "pop", "popitem", "clear",
        "update", "setdefault", "add", "discard", "appendleft", "popleft",
        "sort", "reverse",
    }
)

# Read-modify-write kinds that get flagged (plain ``self.x = <const>``
# rebinds count toward the cross-thread spread but are not themselves
# flagged — a single STORE_ATTR is atomic).
_RMW_KINDS = frozenset({"augassign", "rmw-assign", "container", "item-assign"})


def _is_lockish(name: str) -> bool:
    n = name.lower().strip("_")
    return (
        "lock" in n
        or "mutex" in n
        or n in ("mu", "cv", "cond")
        or n.endswith("_mu")
        or n.endswith("_cv")
        or n.endswith("_cond")
        or n.startswith("cond")
    )


def _terminal_names(node: ast.AST) -> List[str]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute):
            out.append(sub.attr)
        elif isinstance(sub, ast.Name):
            out.append(sub.id)
    return out


def _is_lock_context(item: ast.withitem) -> bool:
    return any(_is_lockish(n) for n in _terminal_names(item.context_expr))


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (one level only — ``self.a.b`` returns None so
    mutating a sub-object isn't misattributed to the holder)."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _reads_self_attr(expr: ast.AST, attr: str) -> bool:
    for sub in ast.walk(expr):
        if _self_attr(sub) == attr:
            return True
    return False


@dataclass
class _Mutation:
    attr: str
    line: int
    kind: str  # augassign | rmw-assign | container | item-assign | assign
    locked: bool


@dataclass
class _MethodInfo:
    name: str
    self_calls: Set[str] = field(default_factory=set)
    mutations: List[_Mutation] = field(default_factory=list)
    spawn_targets: List[str] = field(default_factory=list)  # entry methods


class _MethodVisitor(ast.NodeVisitor):
    """One pass over a method body: ``self.*()`` call edges, spawn/submit
    targets, and mutation sites with their lexical lock depth.

    Nested ``def``s are collected as pseudo-methods (``parent.nested``) with
    their own mutation/call info: they close over the same ``self`` but run
    whenever they are *called* — typically as a closure ``Thread`` target,
    the dominant spawn idiom in this codebase — so their sites must not
    inherit the parent's context or its lexical lock depth."""

    def __init__(
        self, info: _MethodInfo, extras: Optional[Dict[str, "_MethodInfo"]] = None
    ) -> None:
        self.info = info
        self.extras: Dict[str, _MethodInfo] = extras if extras is not None else {}
        self._nested: Dict[str, str] = {}  # local def name -> qualified name
        self._lock_depth = 0

    # -- nested defs (closure thread targets) --------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def _visit_nested(self, node) -> None:
        qual = f"{self.info.name}.{node.name}"
        child = _MethodInfo(name=qual)
        visitor = _MethodVisitor(child, self.extras)
        for stmt in node.body:
            visitor.visit(stmt)
        self.extras[qual] = child
        self._nested[node.name] = qual

    # -- lock scopes --------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        locked = any(_is_lock_context(item) for item in node.items)
        for item in node.items:
            self.visit(item)
        if locked:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if locked:
            self._lock_depth -= 1

    # -- call edges + spawn targets -----------------------------------------

    def _callable_targets(self, node: ast.AST) -> List[str]:
        """Methods of self a callable expression would run: ``self.X``, a
        nested closure ``def``, ``lambda: self.X(...)``,
        ``functools.partial(self.X, ...)``."""
        if isinstance(node, ast.Name) and node.id in self._nested:
            return [self._nested[node.id]]
        if isinstance(node, ast.Lambda):
            out = []
            for sub in ast.walk(node.body):
                if isinstance(sub, ast.Call):
                    name = _self_attr(sub.func)
                    if name:
                        out.append(name)
            return out
        if isinstance(node, ast.Call):  # functools.partial(self.X, ...)
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "partial" or (
                isinstance(fn, ast.Name) and fn.id == "partial"
            ):
                if node.args:
                    name = _self_attr(node.args[0])
                    return [name] if name else []
            return []
        name = _self_attr(node)
        return [name] if name else []

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # self.X(...) call edge
        name = _self_attr(func)
        if name:
            self.info.self_calls.add(name)
        # direct call of a nested def: its sites run in THIS context too
        if isinstance(func, ast.Name) and func.id in self._nested:
            self.info.self_calls.add(self._nested[func.id])
        # threading.Thread(target=...)
        if isinstance(func, ast.Attribute) and func.attr == "Thread" or (
            isinstance(func, ast.Name) and func.id == "Thread"
        ):
            for kw in node.keywords:
                if kw.arg == "target":
                    self.info.spawn_targets.extend(
                        self._callable_targets(kw.value)
                    )
        # executor.submit(self.X, ...)
        if isinstance(func, ast.Attribute) and func.attr == "submit" and node.args:
            self.info.spawn_targets.extend(self._callable_targets(node.args[0]))
        # container mutation in ANY expression position (statement-level
        # `self.d.pop(k)` and value-level `x = self.d.pop(k)` alike)
        if isinstance(func, ast.Attribute) and func.attr in _CONTAINER_MUTATORS:
            self._add(_self_attr(func.value), node.lineno, "container")
        self.generic_visit(node)

    # -- mutation sites ------------------------------------------------------

    def _add(self, attr: Optional[str], line: int, kind: str) -> None:
        if attr:
            self.info.mutations.append(
                _Mutation(attr, line, kind, self._lock_depth > 0)
            )

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        target = node.target
        attr = _self_attr(target)
        if attr is None and isinstance(target, ast.Subscript):
            attr = _self_attr(target.value)
        self._add(attr, node.lineno, "augassign")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            for el in (
                target.elts if isinstance(target, (ast.Tuple, ast.List)) else [target]
            ):
                attr = _self_attr(el)
                if attr is not None:
                    kind = (
                        "rmw-assign"
                        if _reads_self_attr(node.value, attr)
                        else "assign"
                    )
                    self._add(attr, node.lineno, kind)
                elif isinstance(el, ast.Subscript):
                    self._add(_self_attr(el.value), node.lineno, "item-assign")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._add(_self_attr(target.value), node.lineno, "item-assign")
            else:
                attr = _self_attr(target)
                self._add(attr, node.lineno, "assign")
        self.generic_visit(node)

    # nested defs are intercepted by visit_FunctionDef above and analyzed
    # as isolated pseudo-methods — their bodies are NOT visited in the
    # parent's context (they run when called, e.g. as a Thread target, not
    # where they are defined).  Lambdas, by contrast, stay attributed to
    # the enclosing method.


def _collect_methods(cls: ast.ClassDef) -> Dict[str, _MethodInfo]:
    methods: Dict[str, _MethodInfo] = {}
    extras: Dict[str, _MethodInfo] = {}  # nested closure pseudo-methods
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _MethodInfo(name=node.name)
            visitor = _MethodVisitor(info, extras)
            for stmt in node.body:
                visitor.visit(stmt)
            methods[node.name] = info
    methods.update(extras)
    return methods


def _closure(start: str, methods: Dict[str, _MethodInfo]) -> Set[str]:
    seen: Set[str] = set()
    stack = [start]
    while stack:
        name = stack.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        stack.extend(methods[name].self_calls)
    return seen


def check_class(cls: ast.ClassDef, rel_path: str) -> List[Finding]:
    methods = _collect_methods(cls)
    if not methods:
        return []

    # entry points: spawned methods (thread targets / executor submits)
    spawned: Set[str] = set()
    for info in methods.values():
        spawned.update(t for t in info.spawn_targets if t in methods)
    if not spawned:
        return []  # single-context class: nothing can race

    # context labels per method
    contexts: Dict[str, Set[str]] = {name: set() for name in methods}
    for entry in spawned:
        for name in _closure(entry, methods):
            contexts[name].add(f"spawn:{entry}")
    # caller context: the public surface and its closure.  A spawned-only
    # private method stays out of it; an uncalled private method is assumed
    # externally callable (conservative).
    called_by_someone: Set[str] = set()
    for info in methods.values():
        called_by_someone.update(info.self_calls)
    caller_seeds = [
        name
        for name in methods
        if "." not in name  # nested closures are never externally callable
        and (
            (not name.startswith("_") or name.startswith("__"))
            or (name not in spawned and name not in called_by_someone)
        )
    ]
    for seed in caller_seeds:
        for name in _closure(seed, methods):
            contexts[name].add("caller")

    # group mutations by attribute
    per_attr: Dict[str, List[Tuple[str, _Mutation]]] = {}
    for name, info in methods.items():
        for mut in info.mutations:
            per_attr.setdefault(mut.attr, []).append((name, mut))

    findings: List[Finding] = []
    for attr, sites in sorted(per_attr.items()):
        labels: Set[str] = set()
        for method_name, _mut in sites:
            labels.update(contexts[method_name])
        if len(labels) < 2:
            continue
        for method_name, mut in sites:
            if mut.kind not in _RMW_KINDS or mut.locked:
                continue
            if method_name.endswith("_locked"):
                continue  # caller-holds-lock convention
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path,
                    line=mut.line,
                    symbol=f"{cls.name}.{method_name}.{attr}",
                    message=(
                        f"{cls.name}.{attr} is mutated from multiple thread "
                        f"contexts ({', '.join(sorted(labels))}) but this "
                        f"{mut.kind} in {method_name}() is not under a lock"
                    ),
                )
            )
    return findings


def check_source(source: str, rel_path: str) -> List[Finding]:
    tree = ast.parse(source)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            findings.extend(check_class(node, rel_path))
    return findings


def check(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_py_files(root, ["torchft_tpu"]):
        with open(os.path.join(root, rel)) as f:
            source = f.read()
        findings.extend(check_source(source, rel))
    return findings
