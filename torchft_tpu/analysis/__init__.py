"""``ftlint`` — repo-specific static analysis for the torchft_tpu stack.

Eight AST/text checkers enforce the invariants that keep a heavily
concurrent fault-tolerance control plane coherent, the ones the bug record
shows reviewers keep having to catch by hand:

- ``thread-safety`` (:mod:`.threads`): builds a thread-entry graph per
  class (``threading.Thread`` targets, executor ``submit`` targets, and
  everything transitively reachable — RPC handlers ride the accept-loop's
  reachability) and flags read-modify-write mutations of ``self.*`` state
  reachable from two or more entry points that are not lexically under a
  ``with <lock>`` — the ``_inflight_ops +=`` bug class, found statically.
- ``lock-order`` (:mod:`.concurrency`): per-class lock-acquisition graph
  (nested ``with`` scopes, including across ``self._method()`` calls) —
  cycles are potential deadlocks, and re-acquiring a plain ``Lock`` on the
  same thread is a certain one.
- ``blocking-under-lock`` (:mod:`.concurrency`): RPC round-trips, socket
  IO, ``Future.result()``, ``Event.wait()``, ``join()``, ``time.sleep``
  reachable while a lock is lexically held — the quorum-wedge shape.
- ``executor-starvation`` (:mod:`.concurrency`): submits onto a
  single-thread executor from code that itself runs on that executor
  (the task queues behind its submitter; waiting on it self-deadlocks).
- ``wire-protocol`` (:mod:`.wireproto`): every data-plane tag literal must
  come from the central registry in ``wire.py`` (no more scattered 103 /
  880 / 900 / 4000... constants), registered allocations must not collide,
  and every ``encode``/``decode`` pair in ``wire.py`` must be symmetric
  per wire-version gate — a field serialized under
  ``manager_quorum_wire_version() >= N`` must be parsed under the same
  guard, so a one-sided tail can never silently desync rolling upgrades.
- ``knob-registry`` (:mod:`.knobcheck`): every ``TORCHFT_*`` / ``TPUFT_*``
  environment knob mentioned in source must be declared in
  ``torchft_tpu/knobs.py``, and the knob table in ``docs/operations.md``
  must agree with the registry in both directions.
- ``metrics-registry`` (:mod:`.metricscheck`): every name served on a
  ``/metrics`` endpoint must be declared exactly once in
  ``torchft_tpu/obs/metrics.py``, Prometheus-legal (counters end in
  ``_total``), documented in ``docs/operations.md`` §17, and every
  metric-shaped literal in source must name a declared metric.
- ``native-mirror`` (:mod:`.nativemirror`): the hand-mirrored constants
  shared with the C++ tier (``native/comm.h`` / ``native/wire.h`` — lane
  hello flag, 64-byte stripe alignment, frame cap, message types, the
  ``lane_parts`` / ``outer_shard_parts`` / ``HostTopology`` mirrors) must
  match their Python counterparts so the tiers can't drift apart silently.
- ``native-locks`` (:mod:`.nativelocks`): C++ lock discipline, textually —
  ``// guards`` annotations enforced, raw deref of ``*_snapshot()``-style
  members banned (the torn-``EpochIO``-pointer class), dead mutexes and
  atomic/plain mixing flagged.

Run ``python -m torchft_tpu.analysis`` from the repo root (CI does).  A
finding is suppressed either by an inline pragma on its line —
``# ftlint: ignore[<checker>] — <why>`` — or by a fingerprint in
``torchft_tpu/analysis/baseline.json`` (grandfathered violations only;
keep it near-empty).  See ``docs/analysis.md``.
"""

from torchft_tpu.analysis.core import (  # noqa: F401
    Finding,
    load_baseline,
    run_checkers,
)

CHECKERS = (
    "thread-safety",
    "lock-order",
    "blocking-under-lock",
    "executor-starvation",
    "wire-protocol",
    "knob-registry",
    "metrics-registry",
    "native-mirror",
    "native-locks",
)
