"""Concurrency deep-analysis: lock ordering, blocking-under-lock, executor
starvation.

PR 7's ``thread-safety`` checker finds *unlocked* cross-thread mutation; the
three checkers here find the bugs that happen when the locks ARE there:

- ``lock-order``: per class, extracts the lock-acquisition graph — an edge
  A -> B when ``with <B>:`` is entered while A is lexically held, including
  acquisitions reached through ``self._method()`` calls made under A — and
  flags cycles (two threads entering the cycle from different ends deadlock)
  plus same-thread re-acquisition of a plain ``threading.Lock`` (immediate
  self-deadlock; ``RLock``/``Condition`` are reentrant and exempt).
- ``blocking-under-lock``: flags calls that can block indefinitely — RPC
  client calls, socket send/recv, ``Future.result()``, ``Event.wait()``,
  ``Thread.join()``, ``time.sleep`` — made while a lock is lexically held,
  either directly in the ``with`` body or through the transitive
  ``self._method()`` closure entered under the lock.  This is the classic
  quorum-wedge shape: one stuck RPC holds the lock every other thread needs.
  ``cv.wait()`` on the lock being held is exempt (wait releases it).
- ``executor-starvation``: identifies single-thread executors
  (``ThreadPoolExecutor(max_workers=1)`` members) and flags ``submit`` calls
  onto such an executor from code that itself runs ON that executor (the
  submitted task can never start while its submitter occupies the only
  worker — waiting on it self-deadlocks, and even fire-and-forget submits
  queue behind the current task, inverting the intended ordering).

All three share the lexical model of :mod:`.threads`: nested ``def``s are
pseudo-methods that do NOT inherit the parent's lock depth (they run where
they are *called*), lambdas are opaque (their bodies run later, not under
the enclosing locks), and lock recognition follows ``threads._is_lockish``.
Suppress a justified site with ``# ftlint: ignore[<checker>] — <reason>``.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from torchft_tpu.analysis.core import Finding, iter_py_files
from torchft_tpu.analysis.threads import _is_lockish, _self_attr, _terminal_names

LOCK_ORDER = "lock-order"
BLOCKING = "blocking-under-lock"
STARVATION = "executor-starvation"

# module-level wire helpers that do socket IO (torchft_tpu/wire.py); calling
# one while holding a lock is a blocking-under-lock site like sock.recv
_BLOCKING_NAMES = frozenset(
    {"send_frame", "recv_frame", "recv_exact", "connect", "sleep"}
)

# socket / channel methods that block on the peer
_BLOCKING_SOCKET_ATTRS = frozenset(
    {
        "recv", "recv_into", "recvfrom", "recvmsg", "send", "sendall",
        "sendmsg", "accept", "connect", "select",
    }
)


def _lock_name(expr: ast.AST) -> Optional[str]:
    """Identity of a lock context-manager expression: ``self._lock`` ->
    ``_lock``, a bare name -> itself, ``self._x.r_lock()`` -> ``_x``.
    None when nothing in the expression looks lockish."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    attr = _self_attr(expr)
    if attr is not None:
        return attr if _is_lockish(attr) else None
    if isinstance(expr, ast.Name):
        return expr.id if _is_lockish(expr.id) else None
    if isinstance(expr, ast.Attribute):
        # self._x.r_lock() / self._x.w_lock(): the holder attribute is the
        # lock identity (rwlock wrappers)
        inner = _self_attr(expr.value)
        if inner is not None and any(_is_lockish(n) for n in _terminal_names(expr)):
            return inner
    names = [n for n in _terminal_names(expr) if _is_lockish(n)]
    return names[-1] if names else None


@dataclass
class _Acquire:
    held: Tuple[str, ...]
    lock: str
    line: int


@dataclass
class _CallSite:
    held: Tuple[str, ...]
    callee: str
    line: int


@dataclass
class _BlockSite:
    held: Tuple[str, ...]
    desc: str
    line: int


@dataclass
class _SubmitSite:
    executor: str
    targets: Tuple[str, ...]
    line: int


@dataclass
class _MInfo:
    name: str
    acquires: List[_Acquire] = field(default_factory=list)
    calls: List[_CallSite] = field(default_factory=list)
    blocks: List[_BlockSite] = field(default_factory=list)
    submits: List[_SubmitSite] = field(default_factory=list)


class _Visitor(ast.NodeVisitor):
    """One pass over a method body collecting lock acquisitions (with the
    lexically-held set at each), self-call sites, blocking-call sites, and
    executor submits.  Mirrors threads._MethodVisitor's nesting rules."""

    def __init__(self, info: _MInfo, extras: Dict[str, _MInfo]) -> None:
        self.info = info
        self.extras = extras
        self._nested: Dict[str, str] = {}
        self._held: List[str] = []

    # -- nested defs / lambdas ----------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested(node)

    def _visit_nested(self, node) -> None:
        qual = f"{self.info.name}.{node.name}"
        child = _MInfo(name=qual)
        visitor = _Visitor(child, self.extras)
        for stmt in node.body:
            visitor.visit(stmt)
        self.extras[qual] = child
        self._nested[node.name] = qual

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # runs later, not under the enclosing locks

    # -- lock scopes ---------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        entered: List[str] = []
        for item in node.items:
            self.visit(item.context_expr)
            lock = _lock_name(item.context_expr)
            if lock is not None:
                self.info.acquires.append(
                    _Acquire(tuple(self._held), lock, item.context_expr.lineno)
                )
                self._held.append(lock)
                entered.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self._held.pop()

    # -- call sites ----------------------------------------------------------

    def _submit_targets(self, node: ast.AST) -> Tuple[str, ...]:
        out: List[str] = []
        if isinstance(node, ast.Name) and node.id in self._nested:
            out.append(self._nested[node.id])
        name = _self_attr(node)
        if name:
            out.append(name)
        if isinstance(node, ast.Lambda):
            for sub in ast.walk(node.body):
                if isinstance(sub, ast.Call):
                    n = _self_attr(sub.func)
                    if n:
                        out.append(n)
        if isinstance(node, ast.Call):  # functools.partial(self.X, ...)
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "partial") or (
                isinstance(fn, ast.Name) and fn.id == "partial"
            ):
                if node.args:
                    n = _self_attr(node.args[0])
                    if n:
                        out.append(n)
        return tuple(out)

    def _blocking_desc(self, node: ast.Call) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name):
            if func.id in _BLOCKING_NAMES and func.id != "sleep":
                return f"{func.id}() (socket IO)"
            if func.id == "sleep":
                return "sleep()"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value
        if attr == "sleep" and isinstance(recv, ast.Name) and recv.id == "time":
            return "time.sleep()"
        if attr == "result":
            return "Future.result()"
        if attr in ("wait", "wait_for"):
            # cv.wait on the lock being held RELEASES it — not a block
            holder = _self_attr(recv)
            if holder is None and isinstance(recv, ast.Name):
                holder = recv.id
            if holder is not None and holder in self._held:
                return None
            return f"{attr}()"
        if attr == "join" and not node.args:
            # thread.join() takes no positional args; str.join(parts) does
            return "join()"
        if attr in _BLOCKING_SOCKET_ATTRS:
            return f"{attr}() (socket IO)"
        # any method on a *client*-named receiver is an RPC round-trip
        # (RpcClient.call and every typed wrapper around it); close() and
        # interrupt() are local socket teardown, not round-trips
        if attr not in ("close", "interrupt"):
            names = "/".join(_terminal_names(recv)).lower()
            if "client" in names or "rpc" in names:
                return f"RPC .{attr}()"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        name = _self_attr(func)
        if name:
            self.info.calls.append(
                _CallSite(tuple(self._held), name, node.lineno)
            )
        if isinstance(func, ast.Name) and func.id in self._nested:
            self.info.calls.append(
                _CallSite(tuple(self._held), self._nested[func.id], node.lineno)
            )
        if isinstance(func, ast.Attribute) and func.attr == "submit" and node.args:
            executor = _self_attr(func.value)
            if executor is not None:
                self.info.submits.append(
                    _SubmitSite(
                        executor, self._submit_targets(node.args[0]), node.lineno
                    )
                )
        desc = self._blocking_desc(node)
        if desc is not None:
            self.info.blocks.append(
                _BlockSite(tuple(self._held), desc, node.lineno)
            )
        self.generic_visit(node)


@dataclass
class _ClassModel:
    name: str
    methods: Dict[str, _MInfo]
    # lock attr -> ctor kind ("Lock" | "RLock" | "Condition" | ...) when a
    # `self.X = threading.Y()` assignment was seen anywhere in the class
    lock_ctors: Dict[str, str]
    # executor attr -> True when ThreadPoolExecutor(max_workers=1)
    single_executors: Set[str]


def _model_class(cls: ast.ClassDef) -> _ClassModel:
    methods: Dict[str, _MInfo] = {}
    extras: Dict[str, _MInfo] = {}
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info = _MInfo(name=node.name)
            visitor = _Visitor(info, extras)
            for stmt in node.body:
                visitor.visit(stmt)
            methods[node.name] = info
    methods.update(extras)

    lock_ctors: Dict[str, str] = {}
    single_executors: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        ctor = _terminal_names(call.func)
        for target in node.targets:
            attr = _self_attr(target)
            if attr is None:
                continue
            for kind in ("RLock", "Condition", "Lock", "Semaphore", "Event"):
                if kind in ctor:
                    lock_ctors[attr] = kind
                    break
            if "ThreadPoolExecutor" in ctor:
                for kw in call.keywords:
                    if (
                        kw.arg == "max_workers"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value == 1
                    ):
                        single_executors.add(attr)
    return _ClassModel(cls.name, methods, lock_ctors, single_executors)


def _closure(start: str, methods: Dict[str, _MInfo]) -> Set[str]:
    seen: Set[str] = set()
    stack = [start]
    while stack:
        name = stack.pop()
        if name in seen or name not in methods:
            continue
        seen.add(name)
        stack.extend(c.callee for c in methods[name].calls)
    return seen


def _transitive_acquires(model: _ClassModel) -> Dict[str, Set[str]]:
    """Locks acquired anywhere in each method's call closure."""
    out: Dict[str, Set[str]] = {}
    for name in model.methods:
        locks: Set[str] = set()
        for m in _closure(name, model.methods):
            locks.update(a.lock for a in model.methods[m].acquires)
        out[name] = locks
    return out


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------


def _lock_order_findings(model: _ClassModel, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []
    acquires_star = _transitive_acquires(model)

    # edges: (held_lock -> acquired_lock) with a representative site
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}  # -> (method, line, via)
    for name, info in model.methods.items():
        for acq in info.acquires:
            for held in acq.held:
                edges.setdefault(
                    (held, acq.lock), (name, acq.line, "")
                )
        for call in info.calls:
            if not call.held:
                continue
            for lock in acquires_star.get(call.callee, set()):
                for held in call.held:
                    edges.setdefault(
                        (held, lock),
                        (name, call.line, f" via self.{call.callee}()"),
                    )

    # self-deadlock: re-acquiring a plain Lock on the same thread.  RLock
    # and Condition (which wraps an RLock by default) are reentrant; when
    # the ctor is unseen the type is unknown — stay quiet.
    for (a, b), (method, line, via) in sorted(edges.items()):
        if a == b and model.lock_ctors.get(a) == "Lock":
            findings.append(
                Finding(
                    checker=LOCK_ORDER,
                    file=rel_path,
                    line=line,
                    symbol=f"{model.name}.{a}.self-deadlock",
                    message=(
                        f"{model.name}.{method}() re-acquires plain Lock "
                        f"self.{a} while already holding it{via} — "
                        f"threading.Lock is not reentrant; this deadlocks "
                        f"the calling thread"
                    ),
                )
            )

    # cycles among distinct locks: Tarjan SCCs over the edge graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        if a != b:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
    for scc in _sccs(graph):
        if len(scc) < 2:
            continue
        cycle = sorted(scc)
        # a representative pair of conflicting edges for the message
        sites = []
        for (a, b), (method, line, via) in sorted(edges.items()):
            if a in scc and b in scc and a != b:
                sites.append(f"{method}():{line} takes {b} under {a}{via}")
        findings.append(
            Finding(
                checker=LOCK_ORDER,
                file=rel_path,
                line=min(
                    line
                    for (a, b), (_m, line, _v) in edges.items()
                    if a in scc and b in scc and a != b
                ),
                symbol=f"{model.name}.cycle.{'<->'.join(cycle)}",
                message=(
                    f"{model.name} acquires locks {{{', '.join(cycle)}}} in "
                    f"conflicting orders ({'; '.join(sites)}) — two threads "
                    f"entering from different ends deadlock"
                ),
            )
        )
    return findings


def _sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    """Tarjan's strongly-connected components (iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if low[v] == index[v]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == v:
                        break
                out.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[v])

    for node in sorted(graph):
        if node not in index:
            strongconnect(node)
    return out


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


def _blocking_findings(model: _ClassModel, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []

    # transitive blocking descriptions per method (any held-ness inside the
    # callee: the caller's lock is held across the whole call either way)
    blocks_star: Dict[str, Set[str]] = {}
    for name in model.methods:
        descs: Set[str] = set()
        for m in _closure(name, model.methods):
            descs.update(b.desc for b in model.methods[m].blocks)
        blocks_star[name] = descs

    for name, info in model.methods.items():
        for block in info.blocks:
            if not block.held:
                continue
            findings.append(
                Finding(
                    checker=BLOCKING,
                    file=rel_path,
                    line=block.line,
                    symbol=f"{model.name}.{name}.{block.held[-1]}.{block.desc}",
                    message=(
                        f"{model.name}.{name}() calls {block.desc} while "
                        f"holding {block.held[-1]} — a stall here wedges "
                        f"every thread contending for the lock"
                    ),
                )
            )
        for call in info.calls:
            if not call.held or call.callee == name:
                continue
            reached = blocks_star.get(call.callee, set())
            if not reached:
                continue
            desc = sorted(reached)[0]
            findings.append(
                Finding(
                    checker=BLOCKING,
                    file=rel_path,
                    line=call.line,
                    symbol=(
                        f"{model.name}.{name}.{call.held[-1]}"
                        f".{call.callee}.{desc}"
                    ),
                    message=(
                        f"{model.name}.{name}() calls self.{call.callee}() "
                        f"while holding {call.held[-1]}, and that call "
                        f"reaches {desc} — the lock is held across the "
                        f"blocking call"
                    ),
                )
            )
    return findings


# ---------------------------------------------------------------------------
# executor-starvation
# ---------------------------------------------------------------------------


def _starvation_findings(model: _ClassModel, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []
    for executor in sorted(model.single_executors):
        entries: Set[str] = set()
        for info in model.methods.values():
            for sub in info.submits:
                if sub.executor == executor:
                    entries.update(
                        t for t in sub.targets if t in model.methods
                    )
        on_executor: Set[str] = set()
        for entry in entries:
            on_executor.update(_closure(entry, model.methods))
        for name in sorted(on_executor):
            for sub in model.methods[name].submits:
                if sub.executor != executor:
                    continue
                findings.append(
                    Finding(
                        checker=STARVATION,
                        file=rel_path,
                        line=sub.line,
                        symbol=f"{model.name}.{name}.{executor}",
                        message=(
                            f"{model.name}.{name}() runs on single-thread "
                            f"executor {executor} (submitted transitively) "
                            f"and submits back onto it — the task queues "
                            f"behind its submitter; waiting on it "
                            f"self-deadlocks"
                        ),
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def check_source(
    source: str, rel_path: str, checkers: Sequence[str] = (LOCK_ORDER, BLOCKING, STARVATION)
) -> List[Finding]:
    tree = ast.parse(source)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        model = _model_class(node)
        if LOCK_ORDER in checkers:
            findings.extend(_lock_order_findings(model, rel_path))
        if BLOCKING in checkers:
            findings.extend(_blocking_findings(model, rel_path))
        if STARVATION in checkers:
            findings.extend(_starvation_findings(model, rel_path))
    return findings


def _check(root: str, checker: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel in iter_py_files(root, ["torchft_tpu"]):
        with open(os.path.join(root, rel)) as f:
            source = f.read()
        findings.extend(check_source(source, rel, (checker,)))
    return findings


def check_lock_order(root: str) -> List[Finding]:
    return _check(root, LOCK_ORDER)


def check_blocking(root: str) -> List[Finding]:
    return _check(root, BLOCKING)


def check_starvation(root: str) -> List[Finding]:
    return _check(root, STARVATION)
