"""Native lock-discipline checker: C++ concurrency conventions, textually.

The native tier (``native/*.h``, ``*.cc``) is header-only C++ compiled with
no sanitizer in the default build, so its locking discipline is enforced the
same way :mod:`.nativemirror` enforces the wire mirror: by parsing the text
(no C++ toolchain needed at lint time).  Four conventions are checked:

1. **Guards annotations** — a ``// guards a_/b_/c_`` comment above a
   ``std::mutex`` member declares which members that mutex protects (the
   convention ``comm.h`` already documents for ``state_mu_``).  Every use
   of a guarded member must then appear inside a lexical
   ``lock_guard``/``unique_lock``/``scoped_lock`` scope of that mutex.
   Member declarations and constructor-initializer-list entries are exempt.
   Only ``name_``-suffixed members can be annotated (the class-member
   naming convention) — short unsuffixed names like ``q`` would false-match
   locals.
2. **Snapshot discipline** — a member with a ``<stem>_snapshot()`` accessor
   (``io_`` / ``io_snapshot()``, ``pool_`` / ``pool_snapshot()``) must never
   be *dereferenced* through the raw member (``io_->``): configure() swaps
   these pointers under the state mutex while superseded op threads may
   still be mid-IO, so the only sanctioned access is copying the
   ``shared_ptr`` out under the lock — exactly the torn-``EpochIO``-pointer
   UB the PR 8 review caught by hand.
3. **Mutex liveness** — every declared ``std::mutex`` must be acquired
   somewhere in the file; a mutex no ``lock_guard`` ever names is either
   dead weight or, worse, state that silently lost its lock.
4. **Atomic/plain mixing** — a ``std::atomic`` member must not be handed to
   ``memcpy``/``memset``/``memmove`` (bypasses the atomic access path), and
   the same member name must not be declared both atomic and plain in one
   file (a stale shadow of a field that was made atomic).

Suppress a justified site with ``// ftlint: ignore[native-locks] — reason``
on the line or the line above.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Set, Tuple

from torchft_tpu.analysis.core import Finding

CHECKER = "native-locks"

_NATIVE_DIR = "native"

_GUARDS_RE = re.compile(r"//\s*guards\s+(.+)$", re.M)
_MUTEX_DECL_RE = re.compile(
    r"^\s*(?:mutable\s+)?std::mutex\s+(\w+)\s*;", re.M
)
_ATOMIC_DECL_RE = re.compile(
    r"std::atomic<[^>]+>(?:\[\])?>?\s+(\w+)\s*[;{=]"
)
_LOCK_ACQ_RE = re.compile(
    r"std::(?:lock_guard|unique_lock|scoped_lock)\s*<[^>]*>\s*\w+\s*\(([^)]*)\)"
)
_SNAPSHOT_FN_RE = re.compile(r"\b(\w+)_snapshot\s*\(")


def _finding(rel: str, line: int, symbol: str, message: str) -> Finding:
    return Finding(
        checker=CHECKER, file=rel, line=line, symbol=symbol, message=message
    )


def _line_at(text: str, pos: int) -> int:
    return text.count("\n", 0, pos) + 1


def _strip(text: str) -> str:
    """Blank comments and string/char literals, preserving offsets, so
    member-name matching never fires inside prose or log strings."""
    out = list(text)
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == "/" and i + 1 < n and text[i + 1] == "/":
            j = text.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
        elif c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            for k in range(i, j):
                if out[k] != "\n":
                    out[k] = " "
            i = j
        elif c in "\"'":
            quote = c
            j = i + 1
            while j < n and text[j] != quote:
                if text[j] == "\\":
                    j += 1
                j += 1
            j = min(j + 1, n)
            for k in range(i + 1, j - 1):
                if out[k] != "\n":
                    out[k] = " "
            i = j
        else:
            i += 1
    return "".join(out)


def _guard_map(text: str) -> Dict[str, str]:
    """``member -> mutex`` from ``// guards a_/b_`` annotations (raw text —
    the annotation lives in a comment).  The annotation binds to the next
    ``std::mutex`` declaration within the following few lines, and the
    member list may wrap onto ``//`` continuation lines — every
    ``name_``-suffixed token between ``guards`` and the declaration is part
    of the guarded set (a first-line-only parse would silently drop the
    wrapped members and stop enforcing them)."""
    out: Dict[str, str] = {}
    for m in _GUARDS_RE.finditer(text):
        annotation = m.group(1)
        tail = text[m.end():]
        # consume continuation comment lines up to the mutex declaration
        for line in tail.splitlines()[1:]:
            if not line.lstrip().startswith("//"):
                break
            annotation += " " + line
        members = re.findall(r"\b([a-z]\w*_)\b", annotation)
        decl = _MUTEX_DECL_RE.search(tail[:500])
        if not decl:
            continue
        mutex = decl.group(1)
        for member in members:
            if member != mutex:
                out[member] = mutex
    return out


def _lock_scopes(stripped: str) -> List[Tuple[str, int, int]]:
    """(mutex, start, end) byte ranges where each mutex is lexically held:
    from the guard's construction to the close of its enclosing block."""
    scopes: List[Tuple[str, int, int]] = []
    for m in _LOCK_ACQ_RE.finditer(stripped):
        args = m.group(1)
        idents = re.findall(r"\w+", args)
        if not idents:
            continue
        mutex = idents[-1]
        depth = 0
        end = len(stripped)
        for i in range(m.end(), len(stripped)):
            c = stripped[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth < 0:
                    end = i
                    break
        scopes.append((mutex, m.start(), end))
    return scopes


_USE_KEYWORDS = frozenset({"return", "throw", "delete", "co_return", "co_yield"})


def _member_uses(stripped: str, member: str) -> List[int]:
    """Offsets of uses of ``member``, excluding its declaration (a type
    token directly precedes and ``;`` follows — but ``return io_;`` is a
    use, so expression keywords don't count as types) and constructor-
    initializer entries (token followed by ``(``)."""
    uses: List[int] = []
    for m in re.finditer(rf"\b{re.escape(member)}\b", stripped):
        tail = stripped[m.end():m.end() + 2].lstrip()
        if tail.startswith("("):
            continue  # ctor initializer list: io_(std::make_shared<...>())
        if tail.startswith(";") or (tail.startswith("=") and not tail.startswith("==")):
            # `IoPtr io_;` / `uint64_t gen_ = 0;` are declarations when a
            # type token directly precedes; `return io_;` / `gen_ = 1;`
            # (statement context: `;`/`{`/`}` precedes) are uses
            head = stripped[:m.start()].rstrip()
            if head and (head[-1].isalnum() or head[-1] in "_>*&"):
                prev_word = re.search(r"(\w+)$", head)
                if not (prev_word and prev_word.group(1) in _USE_KEYWORDS):
                    continue
        uses.append(m.start())
    return uses


def _locked_fn_ranges(stripped: str) -> List[Tuple[int, int]]:
    """Extents of ``*_locked`` member functions — the caller-holds-lock
    convention (mirror of the Python checker's ``*_locked`` exemption)."""
    out: List[Tuple[int, int]] = []
    for m in re.finditer(r"\b\w+_locked\s*\([^)]*\)(?:\s*const)?\s*\{", stripped):
        depth = 1
        end = len(stripped)
        for i in range(m.end(), len(stripped)):
            c = stripped[i]
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    end = i
                    break
        out.append((m.start(), end))
    return out


def check_text(text: str, rel: str) -> List[Finding]:
    findings: List[Finding] = []
    stripped = _strip(text)
    scopes = _lock_scopes(stripped)
    held_by: Dict[str, List[Tuple[int, int]]] = {}
    for mutex, start, end in scopes:
        held_by.setdefault(mutex, []).append((start, end))
    locked_fns = _locked_fn_ranges(stripped)

    # 1. guards annotations
    for member, mutex in sorted(_guard_map(text).items()):
        ranges = held_by.get(mutex, []) + locked_fns
        for pos in _member_uses(stripped, member):
            if any(start <= pos < end for start, end in ranges):
                continue
            findings.append(
                _finding(
                    rel,
                    _line_at(stripped, pos),
                    f"guards.{member}",
                    f"{member} is annotated `// guards` by {mutex} but this "
                    f"use is outside any lock_guard/unique_lock({mutex}) "
                    f"scope",
                )
            )

    # 2. snapshot discipline: raw deref of members with *_snapshot()
    snapshot_stems: Set[str] = set(_SNAPSHOT_FN_RE.findall(stripped))
    for stem in sorted(snapshot_stems):
        member = stem + "_"
        for m in re.finditer(rf"\b{re.escape(member)}\s*->", stripped):
            findings.append(
                _finding(
                    rel,
                    _line_at(stripped, m.start()),
                    f"snapshot.{member}",
                    f"{member} is dereferenced through the raw member — it "
                    f"has a {stem}_snapshot() accessor because configure() "
                    f"swaps it while superseded op threads are mid-IO; "
                    f"copy the shared_ptr out via {stem}_snapshot() instead "
                    f"(torn-pointer UB otherwise)",
                )
            )

    # 3. mutex liveness
    for m in _MUTEX_DECL_RE.finditer(stripped):
        mutex = m.group(1)
        if mutex in held_by:
            continue
        # condition_variable waits also prove the mutex is live
        if re.search(rf"\bwait(?:_until|_for)?\s*\(\s*\w*{re.escape(mutex)}", stripped):
            continue
        findings.append(
            _finding(
                rel,
                _line_at(stripped, m.start()),
                f"mutex.{mutex}",
                f"std::mutex {mutex} is declared but no "
                f"lock_guard/unique_lock in this file ever acquires it — "
                f"either dead weight or state that lost its lock",
            )
        )

    # 4. atomic/plain mixing
    atomics = set(_ATOMIC_DECL_RE.findall(stripped))
    for member in sorted(atomics):
        for m in re.finditer(
            rf"\bmem(?:cpy|set|move)\s*\([^;]*&\s*{re.escape(member)}\b", stripped
        ):
            findings.append(
                _finding(
                    rel,
                    _line_at(stripped, m.start()),
                    f"atomic.{member}",
                    f"std::atomic member {member} is passed to a raw memory "
                    f"op — this bypasses the atomic access path (plain "
                    f"access mixed with atomic access is a data race)",
                )
            )
        for m in re.finditer(
            rf"^\s*(?:mutable\s+)?(?:bool|int\w*|size_t|uint\w+|float|double)\s+"
            rf"{re.escape(member)}\s*[;=]",
            stripped,
            re.M,
        ):
            findings.append(
                _finding(
                    rel,
                    _line_at(stripped, m.start()),
                    f"atomic.{member}",
                    f"{member} is declared both std::atomic and plain in "
                    f"this file — a stale non-atomic shadow of an "
                    f"atomicized field",
                )
            )
    return findings


def check(root: str) -> List[Finding]:
    findings: List[Finding] = []
    native = os.path.join(root, _NATIVE_DIR)
    if not os.path.isdir(native):
        return [
            _finding(
                _NATIVE_DIR, 1, "dir", "native/ missing — cannot check lock discipline"
            )
        ]
    for name in sorted(os.listdir(native)):
        if not (name.endswith(".h") or name.endswith(".cc")):
            continue
        rel = f"{_NATIVE_DIR}/{name}"
        with open(os.path.join(root, rel)) as f:
            findings.extend(check_text(f.read(), rel))
    return findings
