"""Shared ftlint infrastructure: findings, pragmas, the baseline, the runner.

A :class:`Finding` carries a *fingerprint* that is stable across line-number
drift (checker + file + symbol + message), so baselining a grandfathered
violation survives unrelated edits to the file.  Suppression is two-tier:

- inline pragma ``# ftlint: ignore[<checker>]`` on the finding's line (or
  the line above it) — the preferred form, because the justification lives
  next to the code it excuses;
- the JSON baseline (``torchft_tpu/analysis/baseline.json``) — for
  violations that predate the analyzer and need a tracked debt entry.

Stale baseline entries (fingerprints no checker produces any more) are
reported so the debt list can only shrink.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

# pragmas live in `#` comments in Python and `//` comments in the C++ tier
_PRAGMA_RE = re.compile(r"(?:#|//)\s*ftlint:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass
class Finding:
    checker: str
    file: str  # repo-relative path
    line: int
    symbol: str  # class.method / knob name / tag name / constant name
    message: str

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha1(
            f"{self.checker}|{self.file}|{self.symbol}|{self.message}".encode()
        ).hexdigest()[:12]
        return f"{self.checker}:{self.file}:{self.symbol}:{digest}"

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.checker}] {self.message}"


def repo_root(start: Optional[str] = None) -> str:
    """Walk up from ``start`` (default: this package) to the directory
    holding ``pyproject.toml`` — the scan root everything is relative to."""
    d = os.path.abspath(start or os.path.dirname(os.path.dirname(os.path.dirname(__file__))))
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise FileNotFoundError("pyproject.toml not found above " + str(start))
        d = parent


def iter_py_files(root: str, rel_dirs: Iterable[str]) -> List[str]:
    """Repo-relative paths of every ``.py`` file under the given relative
    dirs (or the single file itself), sorted for deterministic output."""
    out: List[str] = []
    for rel in rel_dirs:
        top = os.path.join(root, rel)
        if os.path.isfile(top):
            out.append(rel)
            continue
        for dirpath, _dirnames, filenames in os.walk(top):
            for name in filenames:
                if name.endswith(".py"):
                    out.append(
                        os.path.relpath(os.path.join(dirpath, name), root)
                    )
    return sorted(out)


def pragma_lines(source: str) -> Dict[int, Tuple[str, ...]]:
    """Map of 1-based line number -> checkers ignored on that line."""
    out: Dict[int, Tuple[str, ...]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(text)
        if m:
            out[i] = tuple(
                part.strip() for part in m.group(1).split(",") if part.strip()
            )
    return out


def is_suppressed(finding: Finding, pragmas: Dict[int, Tuple[str, ...]]) -> bool:
    """A pragma suppresses a finding from its own line or the line above
    (so long mutation statements can carry the pragma on a lead-in
    comment).  ``ignore[all]`` suppresses every checker."""
    for line in (finding.line, finding.line - 1):
        for name in pragmas.get(line, ()):
            if name == "all" or name == finding.checker:
                return True
    return False


def default_baseline_path(root: str) -> str:
    return os.path.join(root, "torchft_tpu", "analysis", "baseline.json")


def load_baseline(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data if isinstance(data, list) else data.get("suppressions", [])
    out = []
    for entry in entries:
        out.append(entry["fingerprint"] if isinstance(entry, dict) else entry)
    return out


def save_baseline(path: str, findings: Iterable[Finding]) -> None:
    payload = {
        "_comment": (
            "ftlint grandfathered violations. Every entry is debt: prefer "
            "an inline `# ftlint: ignore[checker] — reason` pragma next to "
            "the code, and only baseline findings that need a tracked "
            "cross-file exception. See docs/analysis.md."
        ),
        "suppressions": [
            {"fingerprint": f.fingerprint, "note": f.message}
            for f in sorted(findings, key=lambda f: f.fingerprint)
        ],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")


@dataclass
class RunResult:
    new: List[Finding] = field(default_factory=list)  # fail the build
    suppressed: List[Finding] = field(default_factory=list)  # pragma'd
    baselined: List[Finding] = field(default_factory=list)
    stale_baseline: List[str] = field(default_factory=list)
    all_findings: List[Finding] = field(default_factory=list)


def run_checkers(
    root: Optional[str] = None,
    checkers: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
) -> RunResult:
    """Run the requested checkers (default: all four) over the repo at
    ``root`` and partition findings into new / pragma-suppressed /
    baselined."""
    from torchft_tpu.analysis import (
        concurrency,
        knobcheck,
        metricscheck,
        nativelocks,
        nativemirror,
        threads,
        wireproto,
    )

    root = root or repo_root()
    registry = {
        "thread-safety": threads.check,
        "lock-order": concurrency.check_lock_order,
        "blocking-under-lock": concurrency.check_blocking,
        "executor-starvation": concurrency.check_starvation,
        "wire-protocol": wireproto.check,
        "knob-registry": knobcheck.check,
        "metrics-registry": metricscheck.check,
        "native-mirror": nativemirror.check,
        "native-locks": nativelocks.check,
    }
    names = list(checkers) if checkers else list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise ValueError(f"unknown checker(s): {unknown} (have {list(registry)})")

    result = RunResult()
    pragma_cache: Dict[str, Dict[int, Tuple[str, ...]]] = {}
    for name in names:
        for finding in registry[name](root):
            result.all_findings.append(finding)
            if finding.file not in pragma_cache:
                path = os.path.join(root, finding.file)
                try:
                    with open(path) as f:
                        pragma_cache[finding.file] = pragma_lines(f.read())
                except OSError:
                    pragma_cache[finding.file] = {}
            if is_suppressed(finding, pragma_cache[finding.file]):
                result.suppressed.append(finding)
            else:
                result.new.append(finding)

    baseline = set(load_baseline(baseline_path or default_baseline_path(root)))
    if baseline:
        still_new = []
        for finding in result.new:
            if finding.fingerprint in baseline:
                result.baselined.append(finding)
            else:
                still_new.append(finding)
        result.new = still_new
        produced = {f.fingerprint for f in result.all_findings}
        result.stale_baseline = sorted(baseline - produced)
    return result
