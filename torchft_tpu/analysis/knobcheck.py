"""Knob-registry checker: every env knob declared, docs in sync.

Extracts every ``TORCHFT_*`` / ``TPUFT_*`` token from string constants in
package + bench + scripts source (AST-based, so comments don't count) and
requires each to be declared in :mod:`torchft_tpu.knobs`.  Indirection is
free: a ``RETRIES_ENV = "..."`` constant declares the knob literal right
where it is defined, and ``os.environ.get(RETRIES_ENV)`` carries no
literal at all.

Docs drift is checked in both directions against ``docs/operations.md``:

- a knob mentioned in the doc but absent from the registry is a doc for a
  knob that doesn't exist (or was renamed without the doc);
- a registered knob never mentioned in the doc is an undocumented operator
  surface (the generated table in operations.md §13 keeps this green —
  regenerate with ``python -m torchft_tpu.knobs``).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from torchft_tpu.analysis.core import Finding, iter_py_files

CHECKER = "knob-registry"

_KNOB_RE = re.compile(r"\b(?:TORCHFT|TPUFT)_[A-Z0-9]+(?:_[A-Z0-9]+)*\b")
# source roots whose knob mentions must be registered
_SCAN_ROOTS = ("torchft_tpu", "bench.py", "scripts", "benchmarks", "examples")
_DOC_REL = os.path.join("docs", "operations.md")


def knob_tokens_in_source(source: str) -> List[Tuple[str, int]]:
    """(token, line) for every knob-shaped name in a string constant."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ast.parse(source)):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _KNOB_RE.finditer(node.value):
                out.append((m.group(0), node.lineno))
    return out


def _is_prefix_mention(token: str, registry: Dict[str, object]) -> bool:
    """``TPUFT_BENCH`` in a ``startswith("TPUFT_BENCH_")`` filter is a
    family prefix, not a knob."""
    probe = token + "_"
    return any(name.startswith(probe) for name in registry)


def check_source_tokens(
    source: str, rel_path: str, registry: Dict[str, object]
) -> List[Finding]:
    findings = []
    seen: Set[Tuple[str, int]] = set()
    for token, line in knob_tokens_in_source(source):
        if token in registry or _is_prefix_mention(token, registry):
            continue
        if (token, line) in seen:
            continue
        seen.add((token, line))
        findings.append(
            Finding(
                checker=CHECKER,
                file=rel_path,
                line=line,
                symbol=token,
                message=(
                    f"{token} is not declared in torchft_tpu/knobs.py — "
                    f"register it (name, type, default, doc) before use"
                ),
            )
        )
    return findings


def check_docs(
    doc_text: str, registry: Dict[str, object], rel_path: str = _DOC_REL
) -> List[Finding]:
    findings = []
    doc_names: Dict[str, int] = {}
    for i, line_text in enumerate(doc_text.splitlines(), start=1):
        for m in _KNOB_RE.finditer(line_text):
            doc_names.setdefault(m.group(0), i)
    for name, line in sorted(doc_names.items()):
        if name not in registry and not _is_prefix_mention(name, registry):
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=(
                        f"docs/operations.md mentions {name}, which is not "
                        f"in the knob registry — stale doc or unregistered "
                        f"knob"
                    ),
                )
            )
    for name in sorted(set(registry) - set(doc_names)):
        findings.append(
            Finding(
                checker=CHECKER,
                file=rel_path.replace(os.sep, "/"),
                line=1,
                symbol=name,
                message=(
                    f"registered knob {name} is never mentioned in "
                    f"docs/operations.md — add it to the §13 table "
                    f"(python -m torchft_tpu.knobs regenerates it)"
                ),
            )
        )
    return findings


def check(root: str) -> List[Finding]:
    from torchft_tpu import knobs

    registry = knobs.REGISTRY
    findings: List[Finding] = []
    for rel in iter_py_files(root, _SCAN_ROOTS):
        with open(os.path.join(root, rel)) as f:
            source = f.read()
        try:
            findings.extend(check_source_tokens(source, rel, registry))
        except SyntaxError:
            continue  # not this checker's job
    doc_path = os.path.join(root, _DOC_REL)
    if os.path.exists(doc_path):
        with open(doc_path) as f:
            findings.extend(check_docs(f.read(), registry))
    return findings
