"""Native-mirror checker: the C++ tier's hand-mirrored constants.

``native/comm.h`` and ``native/wire.h`` deliberately re-implement the
Python tier's wire math (``lane_parts``, ``outer_shard_parts``,
``HostTopology``, the lane-hello flag, the 64-byte stripe alignment, the
frame cap, the message-type enums) so the two tiers stay byte-compatible
on the wire.  Nothing enforces the mirror — this checker does, by parsing
the headers textually (no C++ toolchain needed at lint time) and comparing
every shared constant against its live Python counterpart:

- ``kMaxFrameBytes``        == ``wire.MAX_FRAME_BYTES``
- ``MsgType`` / ``ErrCode`` values (every native entry must exist in
  Python under the same value; ``ERROR_FRAME`` maps to ``ERROR``)
- ``kLaneHelloFlag``        == ``communicator._LANE_HELLO_FLAG``
- stripe alignment: ``lane_parts``'s ``/ 64 * 64`` cut and
  ``outer_shard_parts``'s ``unit % 64`` / ``unit = 64`` default
  == ``communicator._STRIPE_ALIGN``
- default stripe floor (``stripe_floor_from_env``)
  == ``communicator._MIN_STRIPE_BYTES``
- the ``outer_shard_parts`` padding formula matches the canonical
  ceil-to-unit form, and mirrored symbols (``HostTopology`` with its
  ``worth_it`` auto criterion, ``lane_parts``, ``outer_shard_parts``)
  exist at all.
"""

from __future__ import annotations

import os
import re
from typing import List

from torchft_tpu.analysis.core import Finding

CHECKER = "native-mirror"

_COMM_H = os.path.join("native", "comm.h")
_WIRE_H = os.path.join("native", "wire.h")


def _finding(rel: str, line: int, symbol: str, message: str) -> Finding:
    return Finding(
        checker=CHECKER, file=rel, line=line, symbol=symbol, message=message
    )


def _line_of(text: str, pattern: str) -> int:
    m = re.search(pattern, text)
    return text[: m.start()].count("\n") + 1 if m else 1


def check_wire_header(text: str, rel: str = _WIRE_H) -> List[Finding]:
    from torchft_tpu import wire as pywire

    findings: List[Finding] = []

    m = re.search(r"kMaxFrameBytes\s*=\s*(\d+)ull\s*\*\s*1024\s*\*\s*1024", text)
    if not m:
        findings.append(
            _finding(rel, 1, "kMaxFrameBytes", "kMaxFrameBytes not found in wire.h")
        )
    elif int(m.group(1)) * 1024 * 1024 != pywire.MAX_FRAME_BYTES:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kMaxFrameBytes"),
                "kMaxFrameBytes",
                f"kMaxFrameBytes = {int(m.group(1))} MiB but Python "
                f"wire.MAX_FRAME_BYTES = {pywire.MAX_FRAME_BYTES} bytes",
            )
        )

    name_map = {"ERROR_FRAME": "ERROR"}
    for cname, value_str in re.findall(
        r"^\s*([A-Z][A-Z0-9_]+)\s*=\s*(0x[0-9A-Fa-f]+|\d+)\s*,", text, re.M
    ):
        value = int(value_str, 0)
        if cname.startswith("ERR_"):
            pyname = cname[len("ERR_"):]
            table = {e.name: e.value for e in pywire.ErrCode}
        else:
            pyname = name_map.get(cname, cname)
            table = {e.name: e.value for e in pywire.MsgType}
        if pyname not in table:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, re.escape(cname)),
                    cname,
                    f"native enum {cname} has no Python counterpart "
                    f"({pyname} not in wire.MsgType/ErrCode)",
                )
            )
        elif table[pyname] != value:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, re.escape(cname)),
                    cname,
                    f"native {cname} = {value:#x} but Python "
                    f"{pyname} = {table[pyname]:#x}",
                )
            )
    return findings


def check_comm_header(text: str, rel: str = _COMM_H) -> List[Finding]:
    from torchft_tpu import communicator as pycomm

    findings: List[Finding] = []

    # mirrored symbols must exist at all
    for symbol, pattern in (
        ("HostTopology", r"struct\s+HostTopology"),
        ("HostTopology.worth_it", r"bool\s+worth_it\s*\("),
        ("lane_parts", r"\blane_parts\s*\("),
        ("outer_shard_parts", r"\bouter_shard_parts\s*\("),
        ("kLaneHelloFlag", r"kLaneHelloFlag"),
    ):
        if not re.search(pattern, text):
            findings.append(
                _finding(
                    rel,
                    1,
                    symbol,
                    f"mirrored symbol {symbol} not found in comm.h — the "
                    f"native tier no longer mirrors the Python wire math",
                )
            )

    # lane hello flag
    m = re.search(r"kLaneHelloFlag\s*=\s*uint64_t\(1\)\s*<<\s*(\d+)", text)
    if m and (1 << int(m.group(1))) != pycomm._LANE_HELLO_FLAG:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kLaneHelloFlag"),
                "kLaneHelloFlag",
                f"kLaneHelloFlag = 1<<{m.group(1)} but Python "
                f"_LANE_HELLO_FLAG = {pycomm._LANE_HELLO_FLAG:#x}",
            )
        )

    align = pycomm._STRIPE_ALIGN

    # lane_parts 64-byte cut:  cut = (i * nbytes / k) / 64 * 64
    m = re.search(r"\(i \* nbytes / k\)\s*/\s*(\d+)\s*\*\s*(\d+)", text)
    if m and (int(m.group(1)) != align or int(m.group(2)) != align):
        findings.append(
            _finding(
                rel,
                _line_of(text, r"\(i \* nbytes / k\)"),
                "lane_parts.align",
                f"lane_parts aligns cuts to {m.group(1)} bytes but Python "
                f"_STRIPE_ALIGN = {align}",
            )
        )

    # outer_shard_parts: unit check + default + padding formula
    m = re.search(r"unit\s*%\s*(\d+)\s*!=\s*0", text)
    if m and int(m.group(1)) != align:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"unit\s*%"),
                "outer_shard_parts.unit",
                f"outer_shard_parts requires unit %% {m.group(1)} == 0 but "
                f"Python requires a multiple of {align}",
            )
        )
    m = re.search(r"size_t\s+unit\s*=\s*(\d+)", text)
    if m and int(m.group(1)) != align:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"size_t\s+unit\s*="),
                "outer_shard_parts.default_unit",
                f"outer_shard_parts default unit = {m.group(1)} but Python "
                f"default is _STRIPE_ALIGN = {align}",
            )
        )
    if re.search(r"\bouter_shard_parts\s*\(", text) and not re.search(
        r"share\s*=\s*\(nbytes \+ parts \* unit - 1\)\s*/\s*\(parts \* unit\)\s*\*\s*unit",
        text,
    ):
        findings.append(
            _finding(
                rel,
                _line_of(text, r"outer_shard_parts"),
                "outer_shard_parts.formula",
                "outer_shard_parts share formula drifted from the canonical "
                "ceil(nbytes / (parts*unit)) * unit — Python "
                "communicator.outer_shard_parts computes "
                "-(-nbytes // (parts * unit)) * unit",
            )
        )

    # default stripe floor
    m = re.search(
        r'== "auto"\)\s*return\s+size_t\((\d+)\)\s*<<\s*(\d+);', text
    )
    if m:
        native_floor = int(m.group(1)) << int(m.group(2))
        if native_floor != pycomm._MIN_STRIPE_BYTES:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, r"stripe_floor_from_env"),
                    "stripe_floor",
                    f"native default stripe floor = {native_floor} but "
                    f"Python _MIN_STRIPE_BYTES = {pycomm._MIN_STRIPE_BYTES}",
                )
            )
    return findings


def check(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for rel, fn in ((_WIRE_H, check_wire_header), (_COMM_H, check_comm_header)):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(
                _finding(
                    rel.replace(os.sep, "/"),
                    1,
                    "header",
                    f"{rel} missing — cannot verify the native mirror",
                )
            )
            continue
        with open(path) as f:
            findings.extend(fn(f.read(), rel.replace(os.sep, "/")))
    return findings
