"""Native-mirror checker: the C++ tier's hand-mirrored constants.

``native/comm.h`` and ``native/wire.h`` deliberately re-implement the
Python tier's wire math (``lane_parts``, ``outer_shard_parts``,
``HostTopology``, the lane-hello flag, the 64-byte stripe alignment, the
frame cap, the message-type enums) so the two tiers stay byte-compatible
on the wire.  Nothing enforces the mirror — this checker does, by parsing
the headers textually (no C++ toolchain needed at lint time) and comparing
every shared constant against its live Python counterpart:

- ``kMaxFrameBytes``        == ``wire.MAX_FRAME_BYTES``
- ``MsgType`` / ``ErrCode`` values (every native entry must exist in
  Python under the same value; ``ERROR_FRAME`` maps to ``ERROR``)
- ``kLaneHelloFlag``        == ``communicator._LANE_HELLO_FLAG``
- stripe alignment: ``lane_parts``'s ``/ 64 * 64`` cut and
  ``outer_shard_parts``'s ``unit % 64`` / ``unit = 64`` default
  == ``communicator._STRIPE_ALIGN``
- ``kMinStripeBytes``       == ``communicator._MIN_STRIPE_BYTES``
- ``kMaxAutoLanes``         == ``communicator._MAX_AUTO_LANES``
- ``kRingReduceTagBase``    == ``wire.RING_REDUCE_TAG_BASE``
- ``kMaxIovSegs``           == ``native._MAX_IOV_SEGS`` (the scatter-gather
  framing's per-syscall segment batch, mirrored in the ctypes binding)
- pacer knob names: ``comm.h`` must reference every ``TORCHFT_NET_*`` env
  knob the Python ``_NetEmu`` reads (same pacing model on both tiers), and
  its ``kNetEmuProfiles`` table must match ``communicator._NET_EMU_PROFILES``
  name-for-name and value-for-value in both directions
- per-lane counter names: ``comm.h`` must define the ``lane_tx_bytes`` /
  ``lane_rx_bytes`` / ``lane_stalls`` counters and ``native.py`` must
  export the same ``lane_stats()`` keys the Python tier does, so
  ``manager.last_quorum_timings`` stays tier-agnostic
- flight-recorder event ids: every ``kFlight<Name> = N`` constant in
  ``comm.h`` must match ``obs.flight.FlightEvent.<NAME>`` (CamelCase →
  UPPER_SNAKE) value-for-value, the C ring must exist
  (``tpuft_comm_flight_drain`` + the configure/abort record sites), and
  the binding must mirror the ring slot count
- the ``outer_shard_parts`` padding formula matches the canonical
  ceil-to-unit form, and mirrored symbols (``HostTopology`` with its
  ``worth_it`` auto criterion, ``lane_parts``, ``outer_shard_parts``)
  exist at all.
"""

from __future__ import annotations

import os
import re
from typing import List

from torchft_tpu.analysis.core import Finding

CHECKER = "native-mirror"

_COMM_H = os.path.join("native", "comm.h")
_WIRE_H = os.path.join("native", "wire.h")
_BINDING = os.path.join("torchft_tpu", "native.py")

# the env knobs the Python _NetEmu pacer reads; the native pacer must read
# the same set or cross-tier benches shape only one side of the wire
_PACER_KNOBS = (
    "TORCHFT_NET_EMU",
    "TORCHFT_NET_GBPS",
    "TORCHFT_NET_RTT_MS",
    "TORCHFT_NET_CWND_KB",
)

# the tier-agnostic lane_stats() core keys (TCPCommunicator.lane_stats);
# the native binding must export the same names
_LANE_STAT_KEYS = (
    "lanes",
    "stripe_floor_bytes",
    "lane_tx_bytes",
    "lane_rx_bytes",
    "lane_stalls",
)


def _finding(rel: str, line: int, symbol: str, message: str) -> Finding:
    return Finding(
        checker=CHECKER, file=rel, line=line, symbol=symbol, message=message
    )


def _line_of(text: str, pattern: str) -> int:
    m = re.search(pattern, text)
    return text[: m.start()].count("\n") + 1 if m else 1


def check_wire_header(text: str, rel: str = _WIRE_H) -> List[Finding]:
    from torchft_tpu import wire as pywire

    findings: List[Finding] = []

    m = re.search(r"kMaxFrameBytes\s*=\s*(\d+)ull\s*\*\s*1024\s*\*\s*1024", text)
    if not m:
        findings.append(
            _finding(rel, 1, "kMaxFrameBytes", "kMaxFrameBytes not found in wire.h")
        )
    elif int(m.group(1)) * 1024 * 1024 != pywire.MAX_FRAME_BYTES:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kMaxFrameBytes"),
                "kMaxFrameBytes",
                f"kMaxFrameBytes = {int(m.group(1))} MiB but Python "
                f"wire.MAX_FRAME_BYTES = {pywire.MAX_FRAME_BYTES} bytes",
            )
        )

    name_map = {"ERROR_FRAME": "ERROR"}
    for cname, value_str in re.findall(
        r"^\s*([A-Z][A-Z0-9_]+)\s*=\s*(0x[0-9A-Fa-f]+|\d+)\s*,", text, re.M
    ):
        value = int(value_str, 0)
        if cname.startswith("ERR_"):
            pyname = cname[len("ERR_"):]
            table = {e.name: e.value for e in pywire.ErrCode}
        else:
            pyname = name_map.get(cname, cname)
            table = {e.name: e.value for e in pywire.MsgType}
        if pyname not in table:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, re.escape(cname)),
                    cname,
                    f"native enum {cname} has no Python counterpart "
                    f"({pyname} not in wire.MsgType/ErrCode)",
                )
            )
        elif table[pyname] != value:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, re.escape(cname)),
                    cname,
                    f"native {cname} = {value:#x} but Python "
                    f"{pyname} = {table[pyname]:#x}",
                )
            )
    return findings


def check_comm_header(text: str, rel: str = _COMM_H) -> List[Finding]:
    from torchft_tpu import communicator as pycomm

    findings: List[Finding] = []

    # mirrored symbols must exist at all
    for symbol, pattern in (
        ("HostTopology", r"struct\s+HostTopology"),
        ("HostTopology.worth_it", r"bool\s+worth_it\s*\("),
        ("lane_parts", r"\blane_parts\s*\("),
        ("outer_shard_parts", r"\bouter_shard_parts\s*\("),
        ("kLaneHelloFlag", r"kLaneHelloFlag"),
    ):
        if not re.search(pattern, text):
            findings.append(
                _finding(
                    rel,
                    1,
                    symbol,
                    f"mirrored symbol {symbol} not found in comm.h — the "
                    f"native tier no longer mirrors the Python wire math",
                )
            )

    # lane hello flag
    m = re.search(r"kLaneHelloFlag\s*=\s*uint64_t\(1\)\s*<<\s*(\d+)", text)
    if m and (1 << int(m.group(1))) != pycomm._LANE_HELLO_FLAG:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kLaneHelloFlag"),
                "kLaneHelloFlag",
                f"kLaneHelloFlag = 1<<{m.group(1)} but Python "
                f"_LANE_HELLO_FLAG = {pycomm._LANE_HELLO_FLAG:#x}",
            )
        )

    align = pycomm._STRIPE_ALIGN

    # lane_parts 64-byte cut:  cut = (i * nbytes / k) / 64 * 64
    m = re.search(r"\(i \* nbytes / k\)\s*/\s*(\d+)\s*\*\s*(\d+)", text)
    if m and (int(m.group(1)) != align or int(m.group(2)) != align):
        findings.append(
            _finding(
                rel,
                _line_of(text, r"\(i \* nbytes / k\)"),
                "lane_parts.align",
                f"lane_parts aligns cuts to {m.group(1)} bytes but Python "
                f"_STRIPE_ALIGN = {align}",
            )
        )

    # outer_shard_parts: unit check + default + padding formula
    m = re.search(r"unit\s*%\s*(\d+)\s*!=\s*0", text)
    if m and int(m.group(1)) != align:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"unit\s*%"),
                "outer_shard_parts.unit",
                f"outer_shard_parts requires unit %% {m.group(1)} == 0 but "
                f"Python requires a multiple of {align}",
            )
        )
    m = re.search(r"size_t\s+unit\s*=\s*(\d+)", text)
    if m and int(m.group(1)) != align:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"size_t\s+unit\s*="),
                "outer_shard_parts.default_unit",
                f"outer_shard_parts default unit = {m.group(1)} but Python "
                f"default is _STRIPE_ALIGN = {align}",
            )
        )
    if re.search(r"\bouter_shard_parts\s*\(", text) and not re.search(
        r"share\s*=\s*\(nbytes \+ parts \* unit - 1\)\s*/\s*\(parts \* unit\)\s*\*\s*unit",
        text,
    ):
        findings.append(
            _finding(
                rel,
                _line_of(text, r"outer_shard_parts"),
                "outer_shard_parts.formula",
                "outer_shard_parts share formula drifted from the canonical "
                "ceil(nbytes / (parts*unit)) * unit — Python "
                "communicator.outer_shard_parts computes "
                "-(-nbytes // (parts * unit)) * unit",
            )
        )

    # default stripe floor (kMinStripeBytes) + auto-lane cap (kMaxAutoLanes)
    m = re.search(r"kMinStripeBytes\s*=\s*size_t\((\d+)\)\s*<<\s*(\d+)", text)
    if m:
        native_floor = int(m.group(1)) << int(m.group(2))
        if native_floor != pycomm._MIN_STRIPE_BYTES:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, r"kMinStripeBytes"),
                    "kMinStripeBytes",
                    f"native kMinStripeBytes = {native_floor} but Python "
                    f"_MIN_STRIPE_BYTES = {pycomm._MIN_STRIPE_BYTES}",
                )
            )
    m = re.search(r"kMaxAutoLanes\s*=\s*(\d+)", text)
    if m and int(m.group(1)) != pycomm._MAX_AUTO_LANES:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kMaxAutoLanes"),
                "kMaxAutoLanes",
                f"native kMaxAutoLanes = {m.group(1)} but Python "
                f"_MAX_AUTO_LANES = {pycomm._MAX_AUTO_LANES}",
            )
        )

    # explicit reduce_scatter tag window — a drift here frames the ring at
    # the wrong tags against a Python peer (silent cross-tier corruption)
    m = re.search(r"kRingReduceTagBase\s*=\s*(\d+)", text)
    from torchft_tpu import wire as pywire

    if not m:
        findings.append(
            _finding(
                rel,
                1,
                "kRingReduceTagBase",
                "kRingReduceTagBase not found in comm.h — the native "
                "reduce_scatter no longer mirrors wire.RING_REDUCE_TAG_BASE",
            )
        )
    elif int(m.group(1)) != pywire.RING_REDUCE_TAG_BASE:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kRingReduceTagBase"),
                "kRingReduceTagBase",
                f"native kRingReduceTagBase = {m.group(1)} but Python "
                f"wire.RING_REDUCE_TAG_BASE = {pywire.RING_REDUCE_TAG_BASE}",
            )
        )

    # iovec segment batch: mirrored in the ctypes binding (_MAX_IOV_SEGS)
    from torchft_tpu import native as pynative

    m = re.search(r"kMaxIovSegs\s*=\s*(\d+)", text)
    if not m:
        findings.append(
            _finding(
                rel,
                1,
                "kMaxIovSegs",
                "kMaxIovSegs not found in comm.h — the scatter-gather "
                "framing cap is no longer mirrored",
            )
        )
    elif int(m.group(1)) != pynative._MAX_IOV_SEGS:
        findings.append(
            _finding(
                rel,
                _line_of(text, r"kMaxIovSegs"),
                "kMaxIovSegs",
                f"native kMaxIovSegs = {m.group(1)} but native.py "
                f"_MAX_IOV_SEGS = {pynative._MAX_IOV_SEGS}",
            )
        )

    # pacer knob names: the native Pacer must read the same env surface
    for knob in _PACER_KNOBS:
        if knob not in text:
            findings.append(
                _finding(
                    rel,
                    1,
                    f"pacer.{knob}",
                    f"native pacer does not reference {knob} — the Python "
                    "_NetEmu reads it, so cross-tier benches would shape "
                    "only one side of the wire",
                )
            )

    # pacer profile table: names and (gbps, rtt_ms) values both directions
    native_profiles = {
        name: (float(g), float(r))
        for name, g, r in re.findall(
            r'\{"(\w+)",\s*([\d.]+),\s*([\d.]+)\}', text
        )
    }
    py_profiles = {
        name: (float(g), float(r))
        for name, (g, r) in pycomm._NET_EMU_PROFILES.items()
    }
    if native_profiles:
        for name, vals in py_profiles.items():
            if name not in native_profiles:
                findings.append(
                    _finding(
                        rel,
                        _line_of(text, r"kNetEmuProfiles"),
                        f"pacer.profile.{name}",
                        f"Python _NET_EMU_PROFILES has {name!r} but the "
                        "native kNetEmuProfiles table does not",
                    )
                )
            elif native_profiles[name] != vals:
                findings.append(
                    _finding(
                        rel,
                        _line_of(text, re.escape(name)),
                        f"pacer.profile.{name}",
                        f"native profile {name} = {native_profiles[name]} "
                        f"but Python = {vals}",
                    )
                )
        for name in native_profiles:
            if name not in py_profiles:
                findings.append(
                    _finding(
                        rel,
                        _line_of(text, re.escape(name)),
                        f"pacer.profile.{name}",
                        f"native kNetEmuProfiles has {name!r} but Python "
                        "_NET_EMU_PROFILES does not",
                    )
                )
    elif "kNetEmuProfiles" not in text:
        findings.append(
            _finding(
                rel,
                1,
                "kNetEmuProfiles",
                "kNetEmuProfiles table not found in comm.h — the native "
                "pacer no longer mirrors the Python profile set",
            )
        )

    # per-lane counters: the members feeding the tier-agnostic lane_stats
    for counter in ("lane_tx_bytes", "lane_rx_bytes", "lane_stalls"):
        if counter not in text:
            findings.append(
                _finding(
                    rel,
                    1,
                    f"counter.{counter}",
                    f"native comm.h defines no {counter} counter — the "
                    "tier-agnostic lane_stats surface is broken",
                )
            )

    findings.extend(check_flight_events(text, rel))
    return findings


def _camel_to_upper_snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).upper()


def check_flight_events(text: str, rel: str = _COMM_H) -> List[Finding]:
    """The C-side flight ring's event-id mirror: every ``kFlight<Name>``
    constant must match ``obs.flight.FlightEvent.<NAME>`` value-for-value,
    and the ring itself (drain + record sites) must exist."""
    from torchft_tpu.obs.flight import FlightEvent

    findings: List[Finding] = []
    py_events = {e.name: e.value for e in FlightEvent}
    native_ids = re.findall(
        r"kFlight([A-Za-z0-9]+)\s*=\s*(\d+)\s*;", text
    )
    event_ids = [
        (cname, value)
        for cname, value in native_ids
        if cname not in ("RingSlots",)
    ]
    if not event_ids:
        findings.append(
            _finding(
                rel,
                1,
                "kFlightEvents",
                "no kFlight* event ids found in comm.h — the native tier "
                "no longer mirrors the obs/flight.py event enum",
            )
        )
    for cname, value_str in event_ids:
        pyname = _camel_to_upper_snake(cname)
        value = int(value_str)
        if pyname not in py_events:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, rf"kFlight{cname}"),
                    f"kFlight{cname}",
                    f"native flight event kFlight{cname} has no Python "
                    f"counterpart (FlightEvent.{pyname} missing)",
                )
            )
        elif py_events[pyname] != value:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, rf"kFlight{cname}"),
                    f"kFlight{cname}",
                    f"native kFlight{cname} = {value} but Python "
                    f"FlightEvent.{pyname} = {py_events[pyname]}",
                )
            )
    for symbol, pattern in (
        ("flight_drain", r"\bflight_drain\s*\("),
        ("flight_record.configure", r"flight_record\(kFlightCommConfigure"),
        ("flight_record.abort", r"flight_record\(kFlightCommAbort"),
    ):
        if not re.search(pattern, text):
            findings.append(
                _finding(
                    rel,
                    1,
                    symbol,
                    f"flight-ring symbol {symbol} not found in comm.h — "
                    "the native epoch lifecycle is no longer recorded",
                )
            )
    return findings


def check_binding(text: str, rel: str = _BINDING) -> List[Finding]:
    """The ctypes binding's mirrored surface: lane_stats key parity with
    the Python tier and the iovec batch constant's presence."""
    findings: List[Finding] = []
    if not re.search(r"_MAX_IOV_SEGS\s*=\s*\d+", text):
        findings.append(
            _finding(
                rel,
                1,
                "_MAX_IOV_SEGS",
                "_MAX_IOV_SEGS not found in native.py — the scatter-gather "
                "segment batch is no longer mirrored against comm.h",
            )
        )
    for key in _LANE_STAT_KEYS:
        if f'"{key}"' not in text:
            findings.append(
                _finding(
                    rel,
                    _line_of(text, r"def lane_stats"),
                    f"lane_stats.{key}",
                    f"native.py lane_stats() does not export {key!r} — "
                    "TCPCommunicator.lane_stats() does, so "
                    "manager.last_quorum_timings would lose it on the "
                    "native tier",
                )
            )
    # flight-ring binding: the C-side ring must actually drain into dumps
    if "tpuft_comm_flight_drain" not in text:
        findings.append(
            _finding(
                rel,
                1,
                "tpuft_comm_flight_drain",
                "native.py never calls tpuft_comm_flight_drain — the "
                "C-side flight ring would never merge into Python dumps",
            )
        )
    if not re.search(r"#\s*mirror of comm\.h kFlightRingSlots", text):
        findings.append(
            _finding(
                rel,
                _line_of(text, r"def flight_drain"),
                "flight_drain.cap",
                "flight_drain's drain capacity is not annotated as the "
                "kFlightRingSlots mirror — a comm.h resize would silently "
                "truncate drains",
            )
        )
    return findings


def check_flight_ring_slots(
    comm_text: str, binding_text: str, rel: str = _BINDING
) -> List[Finding]:
    """Cross-file value check: the binding's drain capacity must EQUAL
    comm.h's kFlightRingSlots — a comment alone would let a ring resize
    silently truncate drains."""
    native = re.search(r"kFlightRingSlots\s*=\s*(\d+)", comm_text)
    binding = re.search(
        r"cap\s*=\s*(\d+)\s*#\s*mirror of comm\.h kFlightRingSlots",
        binding_text,
    )
    if not native or not binding:
        return []  # absence findings come from the per-file checks
    if int(native.group(1)) != int(binding.group(1)):
        return [
            _finding(
                rel,
                _line_of(binding_text, r"def flight_drain"),
                "flight_drain.cap",
                f"flight_drain drains at most {binding.group(1)} events "
                f"but comm.h kFlightRingSlots = {native.group(1)} — a "
                f"full native ring would silently truncate at dump time",
            )
        ]
    return []


def check(root: str) -> List[Finding]:
    findings: List[Finding] = []
    texts: dict = {}
    for rel, fn in (
        (_WIRE_H, check_wire_header),
        (_COMM_H, check_comm_header),
        (_BINDING, check_binding),
    ):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(
                _finding(
                    rel.replace(os.sep, "/"),
                    1,
                    "header",
                    f"{rel} missing — cannot verify the native mirror",
                )
            )
            continue
        with open(path) as f:
            texts[rel] = f.read()
        findings.extend(fn(texts[rel], rel.replace(os.sep, "/")))
    if _COMM_H in texts and _BINDING in texts:
        findings.extend(
            check_flight_ring_slots(
                texts[_COMM_H],
                texts[_BINDING],
                _BINDING.replace(os.sep, "/"),
            )
        )
    return findings
