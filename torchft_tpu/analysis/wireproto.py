"""Wire-protocol checker: central tag registry + version-gate symmetry.

Three invariants:

1. **Tag registration.**  Every data-plane tag literal (``tag=`` /
   ``tag_base=`` keyword, or a module-level ``*_TAG*`` constant) must be a
   value declared in ``wire.py``'s central registry
   (``USER_TAG_ALLOCATIONS`` / ``WIRE_TAG_OFFSETS`` /
   ``INTERNAL_TAG_BASES``).  Ad-hoc user tags 0..7 are allowed for
   point-to-point sends.
2. **Allocation collisions.**  USER allocations must be pairwise disjoint
   and live below the lowest user-composed WIRE offset; WIRE offsets must
   be at least 1000 apart (the nominal namespace width).
3. **Pack/unpack symmetry.**  For every class in ``wire.py`` with an
   ``encode(w)``/``decode(r)`` pair, the sequence of primitive field
   operations must match between the two — *per wire-version gate*: a
   field written under ``manager_quorum_wire_version() >= N`` must be read
   under a ``... >= N`` guard, so a one-sided tail cannot desync a rolling
   upgrade.  List fields normalize to ``count + many:<prim>``, nested
   ``encode``/``decode`` to ``many:nested``/``nested``, and the tail
   version marker itself is recognized and dropped on both sides.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from torchft_tpu.analysis.core import Finding, iter_py_files

CHECKER = "wire-protocol"

_PRIMS = frozenset(
    {"u8", "u32", "u64", "i64", "f64", "boolean", "string", "blob", "opt_i64"}
)
# files where raw tag literals are hunted (the data plane)
_TAG_SCAN_DIRS = ("torchft_tpu",)
_ADHOC_TAG_MAX = 7  # small ad-hoc p2p tags stay legal


# ---------------------------------------------------------------------------
# 1 + 2: tag registry
# ---------------------------------------------------------------------------


def _registered_values(wire_mod) -> Dict[int, str]:
    values: Dict[int, str] = {}
    for name, (base, _span) in wire_mod.USER_TAG_ALLOCATIONS.items():
        values[base] = name
    for name, off in wire_mod.WIRE_TAG_OFFSETS.items():
        values[off] = name
    for name, base in wire_mod.INTERNAL_TAG_BASES.items():
        values[base] = name
    return values


def check_allocations(
    user: Dict[str, Tuple[int, int]],
    offsets: Dict[str, int],
    rel_path: str = "torchft_tpu/wire.py",
) -> List[Finding]:
    """Collision rules over a registry (parameterized for fixture tests)."""
    findings: List[Finding] = []
    ranges = sorted(
        (base, base + span, name) for name, (base, span) in user.items()
    )
    for (s1, e1, n1), (s2, e2, n2) in zip(ranges, ranges[1:]):
        if s2 < e1:
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path,
                    line=1,
                    symbol=f"{n1}/{n2}",
                    message=(
                        f"tag allocations {n1} [{s1},{e1}) and {n2} "
                        f"[{s2},{e2}) collide"
                    ),
                )
            )
    # user tags must stay below EVERY wire offset: a raw user tag at or
    # above an offset value aliases that namespace's composed frames (the
    # BROADCAST namespace is offset + buffer index, so this includes it)
    if offsets and ranges:
        top = max(e for _s, e, _n in ranges)
        low = min(offsets.values())
        if top > low:
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path,
                    line=1,
                    symbol="USER_TAG_ALLOCATIONS",
                    message=(
                        f"user tag allocations reach {top} but the lowest "
                        f"wire offset is {low}: raw user tags would alias "
                        f"frames of that namespace"
                    ),
                )
            )
    offs = sorted((v, k) for k, v in offsets.items())
    for (v1, k1), (v2, k2) in zip(offs, offs[1:]):
        if v2 - v1 < 1000:
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path,
                    line=1,
                    symbol=f"{k1}/{k2}",
                    message=(
                        f"wire offsets {k1}={v1} and {k2}={v2} are closer "
                        f"than the 1000-wide namespace they partition"
                    ),
                )
            )
    return findings


def _literal_tags_in_source(source: str, rel_path: str) -> List[Tuple[int, int, str]]:
    """(value, line, context) for every numeric tag literal in the file."""
    out: List[Tuple[int, int, str]] = []
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in ("tag", "tag_base") and isinstance(
                    kw.value, ast.Constant
                ) and isinstance(kw.value.value, int):
                    out.append((kw.value.value, kw.value.lineno, kw.arg))
                # tag=BASE + tag / tag=BASE * k: a literal inside the math
                elif kw.arg in ("tag", "tag_base") and isinstance(
                    kw.value, ast.BinOp
                ):
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Constant) and isinstance(
                            sub.value, int
                        ) and sub.value > _ADHOC_TAG_MAX:
                            out.append((sub.value, sub.lineno, kw.arg))
        elif isinstance(node, ast.Assign):
            for target in node.targets:
                name = target.id if isinstance(target, ast.Name) else None
                if name and "TAG" in name.upper() and isinstance(
                    node.value, ast.Constant
                ) and isinstance(node.value.value, int):
                    out.append((node.value.value, node.lineno, name))
    return out


def check_tag_literals(
    source: str, rel_path: str, registered: Dict[int, str]
) -> List[Finding]:
    findings = []
    for value, line, context in _literal_tags_in_source(source, rel_path):
        if value <= _ADHOC_TAG_MAX:
            continue
        if value in registered:
            continue
        if value >= (1 << 63):
            continue  # control-frame sentinels, not tags
        findings.append(
            Finding(
                checker=CHECKER,
                file=rel_path,
                line=line,
                symbol=str(value),
                message=(
                    f"tag literal {value} ({context}) is not declared in "
                    f"the wire.py tag registry — allocate it in "
                    f"USER_TAG_ALLOCATIONS / WIRE_TAG_OFFSETS and use the "
                    f"named constant"
                ),
            )
        )
    return findings


# ---------------------------------------------------------------------------
# 3: encode/decode symmetry
# ---------------------------------------------------------------------------


class _OpCollector:
    """Emit primitive field ops of an encode/decode body in evaluation
    order, attributed to the wire-version level active at the emit site."""

    def __init__(self, handle: str, is_encode: bool) -> None:
        self.handle = handle  # "w" or "r"
        self.is_encode = is_encode
        self.ops: List[Tuple[int, str]] = []  # (level, op)
        self.level = 0
        # names assigned from a version expression -> the level they gate
        self.version_vars: Dict[str, Optional[int]] = {}

    # -- version guard recognition ------------------------------------------

    def _is_version_expr(self, node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                fn = sub.func
                name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
                if "wire_version" in name or name == "manager_quorum_wire_version":
                    return True
            if isinstance(sub, ast.Name) and sub.id in self.version_vars:
                return True
            if isinstance(sub, ast.Name) and "version" in sub.id.lower():
                return True
        return False

    def _guard_level(self, test: ast.AST) -> Optional[int]:
        """``<version expr> >= N`` anywhere in a test -> N."""
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare) and len(sub.ops) == 1:
                if isinstance(sub.ops[0], ast.GtE) and isinstance(
                    sub.comparators[0], ast.Constant
                ):
                    left = sub.left
                    if self._is_version_expr(left) or (
                        not self.is_encode
                        and isinstance(left, ast.Call)
                        and self._is_reader_call(left) == "u32"
                    ):
                        return int(sub.comparators[0].value)
            if isinstance(sub, ast.Name) and self.version_vars.get(sub.id):
                return self.version_vars[sub.id]
        return None

    def _is_reader_call(self, node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _PRIMS
        ):
            return node.func.attr
        return None

    # -- statement walk ------------------------------------------------------

    def visit_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self.visit_stmt(stmt)

    def visit_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.If):
            level = self._guard_level(stmt.test)
            # a decoder's `if not r.done():` tail guard opens no new level
            if level is not None:
                saved = self.level
                self.level = max(self.level, level)
                self.visit_body(stmt.body)
                self.level = saved
            else:
                self.visit_body(stmt.body)
            self.visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                # version marker read:  tail_version = r.u32()
                if (
                    not self.is_encode
                    and self._is_reader_call(stmt.value) == "u32"
                    and "version" in target.id.lower()
                ):
                    self.version_vars[target.id] = None
                    return
                # has_tail = <version expr >= N and ...>
                level = (
                    self._guard_level(stmt.value)
                    if self._is_version_expr(stmt.value)
                    else None
                )
                if level is not None:
                    self.version_vars[target.id] = level
                    return
        if isinstance(stmt, (ast.For, ast.While)):
            before = len(self.ops)
            for sub in ast.walk(stmt):
                self._maybe_emit_call(sub)
            # loop body ops become many:<op>
            looped = self.ops[before:]
            self.ops[before:] = [(lv, f"many:{op}") for lv, op in looped]
            return
        self._collect_expr(stmt)

    def _collect_expr(self, node: ast.AST) -> None:
        for child in self._eval_order(node):
            self._maybe_emit_call(child)

    def _eval_order(self, node: ast.AST) -> List[ast.AST]:
        """Children in evaluation order (func chain before args)."""
        out: List[ast.AST] = []

        def rec(n: ast.AST) -> None:
            if isinstance(n, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
                out.append(n)  # handled atomically by _maybe_emit_call
                return
            if isinstance(n, ast.Call):
                rec(n.func)
                for a in n.args:
                    rec(a)
                for k in n.keywords:
                    rec(k.value)
                out.append(n)
                return
            for child in ast.iter_child_nodes(n):
                rec(child)

        rec(node)
        return out

    def _maybe_emit_call(self, node: ast.AST) -> None:
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # [X for _ in range(r.u32())]  ->  count + many:<elt op>
            gen = node.generators[0]
            has_count = any(
                self._is_reader_call(sub) == "u32" for sub in ast.walk(gen.iter)
            )
            if has_count:
                self.ops.append((self.level, "count"))
            elt_op = self._op_of(node.elt)
            if elt_op:
                self.ops.append((self.level, f"many:{elt_op}"))
            return
        op = self._op_of(node)
        if op:
            self.ops.append((self.level, op))

    def _op_of(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _PRIMS:
                # encode: w.u32(len(x)) is a count; w.u32(<const/IfExp of
                # consts>) right after opening a versioned block is the tail
                # version marker — drop it (the decode side drops its
                # matching `tail_version = r.u32()` read)
                if self.is_encode and fn.attr == "u32" and node.args:
                    arg = node.args[0]
                    if (
                        isinstance(arg, ast.Call)
                        and isinstance(arg.func, ast.Name)
                        and arg.func.id == "len"
                    ):
                        return "count"
                    if isinstance(arg, ast.Constant) or isinstance(
                        arg, ast.IfExp
                    ):
                        return None  # version marker
                return fn.attr
            if fn.attr == "encode":
                return "nested"
            if fn.attr == "decode":
                return "nested"
        return None


def _method(cls: ast.ClassDef, name: str):
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def check_codec_class(cls: ast.ClassDef, rel_path: str) -> List[Finding]:
    enc = _method(cls, "encode")
    dec = _method(cls, "decode")
    if enc is None or dec is None:
        return []
    enc_args = [a.arg for a in enc.args.args if a.arg != "self"]
    dec_args = [a.arg for a in dec.args.args if a.arg != "self"]
    if not enc_args or not dec_args:
        return []
    enc_col = _OpCollector(enc_args[0], is_encode=True)
    enc_col.visit_body(enc.body)
    dec_col = _OpCollector(dec_args[0], is_encode=False)
    dec_col.visit_body(dec.body)

    findings: List[Finding] = []

    def _norm(op: str) -> str:
        # a list-length prefix is wire-identical to a bare u32 (the decode
        # side may read it into a variable before the comprehension)
        return op.replace("count", "u32")

    levels = sorted(
        {lv for lv, _ in enc_col.ops} | {lv for lv, _ in dec_col.ops}
    )
    for level in levels:
        wrote = [_norm(op) for lv, op in enc_col.ops if lv == level]
        read = [_norm(op) for lv, op in dec_col.ops if lv == level]
        if wrote != read:
            gate = (
                "ungated fields"
                if level == 0
                else f"fields gated on wire version >= {level}"
            )
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path,
                    line=enc.lineno,
                    symbol=f"{cls.name}.encode/decode@v{level}",
                    message=(
                        f"{cls.name}: {gate} are asymmetric — encode writes "
                        f"{wrote} but decode reads {read}; a field "
                        f"serialized under a version gate must be parsed "
                        f"under the same gate"
                    ),
                )
            )
    return findings


def check_codec_source(source: str, rel_path: str) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ast.parse(source)):
        if isinstance(node, ast.ClassDef):
            findings.extend(check_codec_class(node, rel_path))
    return findings


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------


def check(root: str) -> List[Finding]:
    from torchft_tpu import wire

    findings = check_allocations(
        wire.USER_TAG_ALLOCATIONS, wire.WIRE_TAG_OFFSETS
    )
    registered = _registered_values(wire)
    for rel in iter_py_files(root, _TAG_SCAN_DIRS):
        if rel.replace(os.sep, "/").startswith("torchft_tpu/analysis/"):
            continue
        with open(os.path.join(root, rel)) as f:
            source = f.read()
        if rel.replace(os.sep, "/") == "torchft_tpu/wire.py":
            findings.extend(check_codec_source(source, rel))
            continue  # the registry's own declarations aren't "literals"
        findings.extend(check_tag_literals(source, rel, registered))
    return findings
