"""Metrics-registry checker: every /metrics name declared once, legal,
documented, and every serving site registered.

The ``/metrics`` plane (``obs/metrics.py``) declares every served metric
name exactly once.  This checker enforces the contract statically:

1. **Declarations** (``_m("name", "kind", ...)`` in ``obs/metrics.py``):
   parsed textually so a duplicate that would raise at import is caught at
   lint time too; names must be Prometheus-legal
   (``[a-z_:][a-z0-9_:]*``), kinds must be gauge/counter, counters must
   end in ``_total``.
2. **Serving sites**: every ``torchft_lh_*`` / ``torchft_mgr_*`` string
   literal anywhere in package source (AST string constants, so comments
   don't count) must name a declared metric — an undeclared literal is a
   metric that would KeyError at scrape time (or a typo that would
   silently never serve).
3. **Docs**: every declared metric must appear in ``docs/operations.md``
   (the §17 observability runbook carries the generated table —
   ``python -m torchft_tpu.obs.metrics`` re-emits it), and every
   metric-shaped name in the doc must be declared (stale doc detection) —
   the same two-way contract the knob checker enforces.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Tuple

from torchft_tpu.analysis.core import Finding, iter_py_files

CHECKER = "metrics-registry"

_REGISTRY_REL = os.path.join("torchft_tpu", "obs", "metrics.py")
_DOC_REL = os.path.join("docs", "operations.md")
_SCAN_ROOTS = ("torchft_tpu", "bench.py", "scripts", "benchmarks", "examples")

_DECL_RE = re.compile(r'_m\(\s*\n?\s*"(?P<name>[^"]+)",\s*"(?P<kind>[^"]+)"')
_NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")
# metric-shaped tokens: the two namespaces the /metrics plane serves
_METRIC_TOKEN_RE = re.compile(r"\btorchft_(?:lh|mgr)_[a-z0-9_]+\b")


def parse_declarations(source: str) -> List[Tuple[str, str, int]]:
    """(name, kind, line) for every ``_m("...", "...")`` declaration."""
    out = []
    for m in _DECL_RE.finditer(source):
        line = source[: m.start()].count("\n") + 1
        out.append((m.group("name"), m.group("kind"), line))
    return out


def check_declarations(source: str, rel: str = _REGISTRY_REL) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[str, int] = {}
    for name, kind, line in parse_declarations(source):
        if name in seen:
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=(
                        f"metric {name} declared twice (first at line "
                        f"{seen[name]}) — every /metrics name must be "
                        f"declared exactly once"
                    ),
                )
            )
            continue
        seen[name] = line
        if not _NAME_RE.match(name):
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=f"metric {name} is not a legal Prometheus name",
                )
            )
        if kind not in ("gauge", "counter"):
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=f"metric {name} has unknown kind {kind!r}",
                )
            )
        elif kind == "counter" and not name.endswith("_total"):
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=(
                        f"counter {name} must end in _total (Prometheus "
                        f"naming convention)"
                    ),
                )
            )
    return findings


def metric_tokens_in_source(source: str) -> List[Tuple[str, int]]:
    """(token, line) for every metric-shaped name in a string constant."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ast.parse(source)):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _METRIC_TOKEN_RE.finditer(node.value):
                out.append((m.group(0), node.lineno))
    return out


def check_serving_sites(
    source: str, rel_path: str, declared: Dict[str, object]
) -> List[Finding]:
    """Every metric-shaped literal outside the registry must be declared."""
    findings = []
    seen = set()
    for token, line in metric_tokens_in_source(source):
        if token in declared or (token, line) in seen:
            continue
        seen.add((token, line))
        findings.append(
            Finding(
                checker=CHECKER,
                file=rel_path,
                line=line,
                symbol=token,
                message=(
                    f"{token} is not declared in torchft_tpu/obs/metrics.py "
                    f"— an undeclared name KeyErrors at scrape time; "
                    f"register it (name, kind, doc) first"
                ),
            )
        )
    return findings


def check_docs(
    doc_text: str, declared: Dict[str, object], rel_path: str = _DOC_REL
) -> List[Finding]:
    findings = []
    doc_names: Dict[str, int] = {}
    for i, line_text in enumerate(doc_text.splitlines(), start=1):
        for m in _METRIC_TOKEN_RE.finditer(line_text):
            doc_names.setdefault(m.group(0), i)
    for name, line in sorted(doc_names.items()):
        if name not in declared:
            findings.append(
                Finding(
                    checker=CHECKER,
                    file=rel_path.replace(os.sep, "/"),
                    line=line,
                    symbol=name,
                    message=(
                        f"docs/operations.md mentions metric {name}, which "
                        f"is not in the obs/metrics.py registry — stale doc "
                        f"or unregistered metric"
                    ),
                )
            )
    for name in sorted(set(declared) - set(doc_names)):
        findings.append(
            Finding(
                checker=CHECKER,
                file=rel_path.replace(os.sep, "/"),
                line=1,
                symbol=name,
                message=(
                    f"registered metric {name} is never mentioned in "
                    f"docs/operations.md — add it to the §17 table "
                    f"(python -m torchft_tpu.obs.metrics regenerates it)"
                ),
            )
        )
    return findings


def check(root: str) -> List[Finding]:
    findings: List[Finding] = []
    registry_path = os.path.join(root, _REGISTRY_REL)
    if not os.path.exists(registry_path):
        return [
            Finding(
                checker=CHECKER,
                file=_REGISTRY_REL.replace(os.sep, "/"),
                line=1,
                symbol="registry",
                message="obs/metrics.py missing — no metric registry to check",
            )
        ]
    with open(registry_path) as f:
        registry_source = f.read()
    findings.extend(check_declarations(registry_source))
    declared: Dict[str, object] = {
        name: kind for name, kind, _line in parse_declarations(registry_source)
    }
    registry_rel = _REGISTRY_REL.replace(os.sep, "/")
    for rel in iter_py_files(root, _SCAN_ROOTS):
        if rel.replace(os.sep, "/") == registry_rel:
            continue
        with open(os.path.join(root, rel)) as f:
            source = f.read()
        try:
            findings.extend(check_serving_sites(source, rel, declared))
        except SyntaxError:
            continue  # not this checker's job
    doc_path = os.path.join(root, _DOC_REL)
    if os.path.exists(doc_path):
        with open(doc_path) as f:
            findings.extend(check_docs(f.read(), declared))
    return findings
