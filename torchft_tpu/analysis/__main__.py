"""``ftlint`` CLI: ``python -m torchft_tpu.analysis [options]``.

Exit status 0 when every finding is pragma-suppressed or baselined,
1 otherwise (CI gates on this).  ``--write-baseline`` grandfathers the
current findings; keep that list near-empty and justified (see
docs/analysis.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from torchft_tpu.analysis import CHECKERS
from torchft_tpu.analysis.core import (
    default_baseline_path,
    repo_root,
    run_checkers,
    save_baseline,
)


def _emit_json(result) -> None:
    """Machine-readable run result (``--format json``): every finding with
    its fingerprint and disposition, so CI tooling can diff runs."""

    def row(finding, disposition):
        return {
            "checker": finding.checker,
            "file": finding.file,
            "line": finding.line,
            "symbol": finding.symbol,
            "message": finding.message,
            "fingerprint": finding.fingerprint,
            "disposition": disposition,
        }

    payload = {
        "findings": [row(f, "new") for f in result.new]
        + [row(f, "suppressed") for f in result.suppressed]
        + [row(f, "baselined") for f in result.baselined],
        "stale_baseline": result.stale_baseline,
        "counts": {
            "new": len(result.new),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
        },
    }
    json.dump(payload, sys.stdout, indent=2)
    sys.stdout.write("\n")


def _emit_github(result) -> None:
    """GitHub Actions workflow-command lines (``--format github``): each new
    finding becomes an ``::error`` annotation rendered inline on the PR
    diff.  Only NEW findings annotate — suppressed/baselined debt would be
    noise on every PR."""
    for f in sorted(result.new, key=lambda f: (f.file, f.line)):
        # the message is one line by construction; %, CR and LF would need
        # workflow-command escaping if that ever changes
        print(
            f"::error file={f.file},line={f.line},title=ftlint "
            f"{f.checker}::{f.message}"
        )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ftlint", description="torchft_tpu repo-specific static analysis"
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--checker",
        action="append",
        choices=CHECKERS,
        help="run only this checker (repeatable; default: all)",
    )
    parser.add_argument(
        "--baseline", default=None, help="baseline path (default: in-package)"
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather every current finding into the baseline",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="findings only, no summary"
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "github"),
        default="text",
        help=(
            "output format: human text (default), json (full run result "
            "with fingerprints), or github (::error annotation lines CI "
            "surfaces inline on PRs)"
        ),
    )
    args = parser.parse_args(argv)

    root = args.root or repo_root()
    baseline_path = args.baseline or default_baseline_path(root)
    result = run_checkers(
        root=root, checkers=args.checker, baseline_path=baseline_path
    )

    if args.write_baseline:
        # keep still-firing grandfathered entries; only stale ones drop
        keep = result.new + result.baselined
        save_baseline(baseline_path, keep)
        print(f"ftlint: wrote {len(keep)} suppressions to {baseline_path}")
        return 0

    if args.format == "json":
        _emit_json(result)
        return 1 if result.new else 0
    if args.format == "github":
        _emit_github(result)
        return 1 if result.new else 0

    for finding in sorted(result.new, key=lambda f: (f.file, f.line)):
        print(finding.render())
    if not args.quiet:
        parts = [f"{len(result.new)} finding(s)"]
        if result.suppressed:
            parts.append(f"{len(result.suppressed)} pragma-suppressed")
        if result.baselined:
            parts.append(f"{len(result.baselined)} baselined")
        print(f"ftlint: {', '.join(parts)}", file=sys.stderr)
        for fp in result.stale_baseline:
            print(
                f"ftlint: warning: stale baseline entry {fp} (no longer "
                f"produced — remove it)",
                file=sys.stderr,
            )
    return 1 if result.new else 0


if __name__ == "__main__":
    sys.exit(main())
