"""Llama with Mixture-of-Experts FFN layers (expert-parallel).

A Mixtral-style variant of :mod:`torchft_tpu.models.llama`: the dense SwiGLU
FFN in each block is replaced by a switch MoE
(:mod:`torchft_tpu.parallel.moe`), with experts sharded over the ``ep`` mesh
axis and token routing via ``lax.all_to_all``.  Attention/embeddings keep the
dense model's megatron TP layout.

Because expert weights carry a leading ``num_experts`` dim, layers are NOT
stacked under ``lax.scan`` here — the per-layer Python loop keeps each MoE
dispatch its own XLA op (scan would force identical routing shapes anyway;
MoE models are typically shallow-wide, so compile time stays acceptable).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from torchft_tpu.models.llama import Llama, LlamaConfig
from torchft_tpu.parallel.moe import MoE, MoEConfig


@dataclass(frozen=True)
class LlamaMoEConfig(LlamaConfig):
    num_experts: int = 8
    capacity_factor: float = 1.5
    ep_axis: str = "ep"


def llama_moe_debug(ep_axis: str = "ep") -> LlamaMoEConfig:
    return LlamaMoEConfig(
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=128,
        max_seq_len=256,
        dtype=jnp.float32,
        num_experts=4,
        capacity_factor=4.0,
        ep_axis=ep_axis,
    )


class LlamaMoE(Llama):
    """Llama backbone with per-layer expert-parallel MoE FFNs."""

    def __init__(self, config: LlamaMoEConfig, mesh: Optional[Any] = None) -> None:
        super().__init__(config, mesh=mesh)
        self.moe = MoE(
            MoEConfig(
                dim=config.dim,
                ffn_hidden=config.ffn_hidden,
                num_experts=config.num_experts,
                capacity_factor=config.capacity_factor,
                dtype=config.dtype,
            ),
            mesh=mesh,
            ep_axis=config.ep_axis,
        )

    # ------------------------------------------------------------------

    def init(self, key: jax.Array) -> Dict[str, Any]:
        cfg: LlamaMoEConfig = self.config  # type: ignore[assignment]
        # include_ffn=False: the dense FFN stacks (the model's largest
        # allocations) are never materialized
        base = super().init(key, include_ffn=False)
        moe_keys = jax.random.split(jax.random.fold_in(key, 17), cfg.n_layers)
        base["moe_layers"] = [self.moe.init(k) for k in moe_keys]
        return base

    def param_specs(self) -> Dict[str, Any]:
        cfg: LlamaMoEConfig = self.config  # type: ignore[assignment]
        specs = super().param_specs()
        layers = specs["layers"]
        for name in ("w_gate", "w_up", "w_down"):
            del layers[name]
        specs["moe_layers"] = [self.moe.param_specs() for _ in range(cfg.n_layers)]
        return specs

    # ------------------------------------------------------------------

    def apply(self, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
        cfg: LlamaMoEConfig = self.config  # type: ignore[assignment]
        B, S = tokens.shape
        x = params["embed"][tokens].astype(cfg.dtype)
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        rope = self._rope(positions)

        for layer in range(cfg.n_layers):
            lp = {k: v[layer] for k, v in params["layers"].items()}
            # shared attention half (Llama._attn_block); only the FFN differs
            x = self._attn_block(x, lp, rope, positions)
            h = self._rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
            x = x + self.moe.apply(params["moe_layers"][layer], h).astype(cfg.dtype)

        x = self._rms_norm(x, params["final_norm"], cfg.norm_eps)
        return (x @ params["lm_head"]).astype(jnp.float32)

    def num_params(self) -> int:
        cfg: LlamaMoEConfig = self.config  # type: ignore[assignment]
        moe = (
            cfg.dim * cfg.num_experts  # router
            + cfg.num_experts * cfg.dim * cfg.ffn_hidden * 2  # up + down
        )
        return self._embed_params() + cfg.n_layers * (
            self._attn_params_per_layer() + moe
        )
