"""Model zoo for torchft_tpu examples, tests, and benchmarks."""

_LAZY = {
    "SimpleCNN": ("torchft_tpu.models.cnn", "SimpleCNN"),
    "LlamaConfig": ("torchft_tpu.models.llama", "LlamaConfig"),
    "Llama": ("torchft_tpu.models.llama", "Llama"),
}

__all__ = list(_LAZY)


def __getattr__(name: str):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(importlib.import_module(module_name), attr)
