"""Llama-3-family transformer, TPU-first.

The flagship model for torchft_tpu's fault-tolerant training (the reference
trains Llama 3 8B/70B through torchtitan HSDP, ``README.md:62-69``; here the
model is in-repo because the framework is standalone).

Design choices for the TPU/XLA compilation model:

- **Pure functional**: params are a pytree dict; ``apply`` is a pure
  function — jit/pjit/shard_map compose without a module system.
- **Stacked layers + ``lax.scan``**: per-layer weights carry a leading
  ``n_layers`` dim and the decoder runs as one scanned block, so compile
  time is O(1) in depth and XLA pipelines the layer loop.
- **bf16 matmuls on the MXU**: params and activations default to bfloat16
  with fp32 RMSNorm statistics and fp32 logits for the loss.
- **Sharding as data**: :func:`param_specs` returns a PartitionSpec pytree
  matching ``init`` — megatron TP on the head/ffn dims, FSDP on the
  complementary dim, so HSDP = shard_pytree(params, param_specs(...), mesh).
- **Sequence parallelism**: with ``sp > 1`` attention switches to ring
  attention (``torchft_tpu.parallel.ring_attention``) over the ``sp`` axis.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_hidden: int = 14_336
    rope_theta: float = 500_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 8192
    dtype: Any = jnp.bfloat16
    # sequence parallelism: ring attention over this mesh axis when set
    sp_axis: Optional[str] = None
    # rematerialization: recompute activations in the backward pass (the
    # reference leans on torch's activation checkpointing via torchtitan
    # for the same reason).  ``remat=True`` is per-layer ("layer" mode);
    # ``remat_mode`` selects the policy explicitly:
    #   - "none":  save everything (fastest; biggest activation HBM)
    #   - "attn":  recompute only the attention half — attention is the
    #     cheap-to-recompute minority of a layer's FLOPs (~10% extra
    #     hardware work) while its qkv/out tensors are a meaningful bite
    #     of saved bytes; the FFN's big gate/up intermediates stay saved.
    #     The best MFU of the remat modes when it fits.
    #   - "ffn":   recompute only the FFN half — frees the majority of
    #     saved bytes (gate/up, 2×ffn_hidden wide) at ~26% extra hardware
    #     FLOPs
    #   - "layer": recompute whole layers, saving only the [B,S,dim]
    #     layer-boundary residuals — O(layers) less activation HBM (~33%
    #     extra FLOPs); what lets a ~1B-param config train on one chip
    remat: bool = False
    remat_mode: Optional[str] = None  # None → "layer" if remat else "none"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def effective_remat_mode(self) -> str:
        mode = self.remat_mode or ("layer" if self.remat else "none")
        if mode not in ("none", "attn", "ffn", "layer"):
            raise ValueError(f"unknown remat_mode {mode!r}")
        return mode


def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_70b() -> LlamaConfig:
    return LlamaConfig(
        dim=8192, n_layers=80, n_heads=64, n_kv_heads=8, ffn_hidden=28_672
    )


def llama_debug(sp_axis: Optional[str] = None) -> LlamaConfig:
    """Tiny config for tests/dryruns."""
    return LlamaConfig(
        vocab_size=512,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_hidden=128,
        max_seq_len=256,
        dtype=jnp.float32,
        sp_axis=sp_axis,
    )


class Llama:
    def __init__(self, config: LlamaConfig, mesh: Optional[Any] = None) -> None:
        """``mesh`` is required when ``config.sp_axis`` is set: the ring
        attention shard_map needs the concrete mesh object."""
        self.config = config
        self.mesh = mesh

    # ------------------------------------------------------------------
    # params
    # ------------------------------------------------------------------

    def init(self, key: jax.Array, include_ffn: bool = True) -> Dict[str, Any]:
        """``include_ffn=False`` skips the dense FFN stacks (subclasses with
        their own FFN, e.g. MoE, must never materialize them)."""
        cfg = self.config
        k_embed, k_layers, k_out = jax.random.split(key, 3)

        def _norm(k, shape, fan_in):
            return (
                jax.random.normal(k, shape, dtype=jnp.float32) / np.sqrt(fan_in)
            ).astype(cfg.dtype)

        hd = cfg.head_dim
        L = cfg.n_layers
        keys = jax.random.split(k_layers, 7)
        layers = {
            "wq": _norm(keys[0], (L, cfg.dim, cfg.n_heads * hd), cfg.dim),
            "wk": _norm(keys[1], (L, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
            "wv": _norm(keys[2], (L, cfg.dim, cfg.n_kv_heads * hd), cfg.dim),
            "wo": _norm(keys[3], (L, cfg.n_heads * hd, cfg.dim), cfg.n_heads * hd),
            "attn_norm": jnp.ones((L, cfg.dim), dtype=jnp.float32),
            "mlp_norm": jnp.ones((L, cfg.dim), dtype=jnp.float32),
        }
        if include_ffn:
            layers.update(
                {
                    "w_gate": _norm(keys[4], (L, cfg.dim, cfg.ffn_hidden), cfg.dim),
                    "w_up": _norm(keys[5], (L, cfg.dim, cfg.ffn_hidden), cfg.dim),
                    "w_down": _norm(
                        keys[6], (L, cfg.ffn_hidden, cfg.dim), cfg.ffn_hidden
                    ),
                }
            )
        return {
            "embed": _norm(k_embed, (cfg.vocab_size, cfg.dim), cfg.dim),
            "layers": layers,
            "final_norm": jnp.ones(cfg.dim, dtype=jnp.float32),
            "lm_head": _norm(k_out, (cfg.dim, cfg.vocab_size), cfg.dim),
        }

    def param_specs(self) -> Dict[str, Any]:
        """PartitionSpecs matching :meth:`init`.

        Megatron layout: column-parallel (out dim on ``tp``) for wq/wk/wv and
        gate/up, row-parallel (in dim on ``tp``) for wo/w_down; ``fsdp``
        shards the complementary dim.  Embeddings shard vocab on ``tp``.
        Layer-stacked arrays keep the leading layer dim replicated.
        """
        return {
            "embed": P("tp", "fsdp"),
            "layers": {
                "wq": P(None, "fsdp", "tp"),
                "wk": P(None, "fsdp", "tp"),
                "wv": P(None, "fsdp", "tp"),
                "wo": P(None, "tp", "fsdp"),
                "w_gate": P(None, "fsdp", "tp"),
                "w_up": P(None, "fsdp", "tp"),
                "w_down": P(None, "tp", "fsdp"),
                "attn_norm": P(None, None),
                "mlp_norm": P(None, None),
            },
            "final_norm": P(None),
            "lm_head": P("fsdp", "tp"),
        }

    def batch_specs(self) -> Tuple[Any, Any]:
        """(tokens, targets) PartitionSpecs: batch over (dp, fsdp), sequence
        over sp.  FSDP *is* data parallelism (ZeRO): each fsdp shard must
        process its own batch slice — batch over dp alone would replicate
        activations across the fsdp axis and blow HBM at scale (caught by
        ``parallel/rehearsal.py``: 8B at seq 8192 on a dp=1×fsdp=8 group
        costs ~66 GB/chip of activations replicated vs ~8 GB sharded)."""
        spec = (
            P(("dp", "fsdp"), "sp")
            if self.config.sp_axis
            else P(("dp", "fsdp"), None)
        )
        return spec, spec

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------

    @staticmethod
    def _rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
        x32 = x.astype(jnp.float32)
        rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
        return ((x32 / rms) * weight).astype(x.dtype)

    def _rope(self, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.config
        half = cfg.head_dim // 2
        freqs = 1.0 / (
            cfg.rope_theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
        )
        angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B,S,half]
        return jnp.cos(angles), jnp.sin(angles)

    @staticmethod
    def _apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
        # x: [B, S, H, D]; rotate pairs (x1, x2) per RoPE
        x1, x2 = jnp.split(x, 2, axis=-1)
        c = cos[:, :, None, :]
        s = sin[:, :, None, :]
        return jnp.concatenate(
            [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
        ).astype(x.dtype)

    @staticmethod
    def _assumed_backend() -> str:
        """The platform kernel dispatch plans for.  Normally the runtime
        backend; ``TORCHFT_FLASH_PLATFORM`` overrides it so a device-free
        host can trace the TPU program (``parallel/rehearsal.py`` lowers
        the real Mosaic flash kernels for a pod without owning one)."""
        return os.environ.get("TORCHFT_FLASH_PLATFORM") or jax.default_backend()

    @staticmethod
    def _flash_blocks(seq: int) -> Tuple[int, int]:
        """(block_q, block_k) for the flash kernel: env-tunable (the bench
        sweeps them when hunting MFU), clamped to the sequence length.
        A malformed or non-positive override falls back to the 512 default
        (the divisibility gate then decides flash vs naive)."""

        def _env(name: str) -> int:
            try:
                v = int(os.environ.get(name, "512"))
            except ValueError:
                return 512
            return v if v > 0 else 512

        return (
            min(seq, _env("TORCHFT_FLASH_BLOCK_Q")),
            min(seq, _env("TORCHFT_FLASH_BLOCK_K")),
        )

    def _use_flash(self, seq: int) -> bool:
        """Dispatch to the fused Pallas kernel (``ops/flash_attention.py``)
        when it applies: TPU backend (or forced), flash-friendly shapes, no
        ring attention.  ``TORCHFT_FLASH`` = 1 forces on (interpret mode off
        TPU), 0 kills it, unset = auto."""
        cfg = self.config
        if cfg.sp_axis is not None:
            return False
        env = os.environ.get("TORCHFT_FLASH", "")
        if env == "0":
            return False
        # seq % 8: Mosaic requires 8-divisible sublane dims — a 130-long seq
        # in [128, 512) would otherwise pick block_q=seq and fail to lower.
        # the divisibility gate uses the RESOLVED block sizes, so an env
        # override that doesn't divide seq falls back to the naive path
        # instead of crashing the trace
        block_q, block_k = self._flash_blocks(seq)
        if seq < 128 or seq % 8 or seq % block_q or seq % block_k:
            return False
        if getattr(self, "_disable_flash", False):
            return False
        if env == "1":
            return True
        # auto: single-device programs use the bare kernel; multi-device
        # needs a mesh for the shard_map variant (a bare pallas_call is not
        # SPMD-partitionable — inside a tp/fsdp-sharded jit it would force
        # operand replication)
        if self._assumed_backend() != "tpu":
            return False
        return jax.device_count() == 1 or self._flash_mesh() is not None

    def _flash_mesh(self) -> Optional[Any]:
        """The mesh for ``flash_attention_sharded``, if attention under it
        is purely (batch, head)-parallel: dp/tp axes present, sp/ep/pp all
        size 1 (those paths carry their own attention plumbing)."""
        mesh = self.mesh
        if mesh is None or "dp" not in mesh.shape or "tp" not in mesh.shape:
            return None
        for axis in ("sp", "ep", "pp"):
            if mesh.shape.get(axis, 1) != 1:
                return None
        return mesh

    def _attention(
        self,
        q: jax.Array,
        k: jax.Array,
        v: jax.Array,
        positions: jax.Array,
    ) -> jax.Array:
        """Causal GQA attention. q: [B,S,H,D], k/v: [B,S,KV,D]."""
        cfg = self.config

        if self._use_flash(q.shape[1]):
            from torchft_tpu.ops.flash_attention import (
                flash_attention,
                flash_attention_sharded,
            )

            interpret = self._assumed_backend() != "tpu"
            mesh = self._flash_mesh()
            B, _, H, _ = q.shape
            block_q, block_k = self._flash_blocks(q.shape[1])
            mesh_size = (
                1 if mesh is None
                else int(np.prod(list(mesh.shape.values())))
            )
            if mesh_size == 1:
                # bare kernel: single-device programs, or forced via env
                # without a mesh (then operands replicate — caller's call)
                return flash_attention(
                    q, k, v, causal=True, interpret=interpret,
                    block_q=block_q, block_k=block_k,
                )
            bp = mesh.shape["dp"] * mesh.shape.get("fsdp", 1)
            if (
                B % bp == 0  # batch shards over (dp, fsdp)
                and H % mesh.shape["tp"] == 0
                and cfg.n_kv_heads % mesh.shape["tp"] == 0
            ):
                return flash_attention_sharded(
                    q, k, v, mesh=mesh, causal=True, interpret=interpret,
                    block_q=block_q, block_k=block_k,
                )
            # mesh present but shapes don't shard evenly: naive path below

        if cfg.sp_axis is not None:
            # the ring ships GQA K/V un-repeated (group-factor fewer
            # ppermute bytes); the body broadcasts at compute time
            from torchft_tpu.parallel.ring_attention import (
                ring_attention,
                ring_attention_sharded,
            )

            if getattr(self, "_in_manual_sp", False):
                # already inside a manual region over sp (the pp × sp
                # pipeline): use the raw collective form
                return ring_attention(q, k, v, cfg.sp_axis)
            assert self.mesh is not None, "sp requires a mesh on the model"
            return ring_attention_sharded(
                q, k, v, mesh=self.mesh, sp_axis=cfg.sp_axis
            )

        groups = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)

        scale = 1.0 / np.sqrt(cfg.head_dim)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        seq = q.shape[1]
        causal = jnp.tril(jnp.ones((seq, seq), dtype=bool))
        scores = jnp.where(causal[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", probs, v)

    def _attn_block(
        self, x: jax.Array, layer_params: Dict[str, jax.Array], rope, positions
    ) -> jax.Array:
        """Pre-norm RoPE/GQA attention + residual — shared by dense and MoE
        variants (the FFN half is the pluggable part)."""
        cfg = self.config
        cos, sin = rope
        B, S, _ = x.shape
        hd = cfg.head_dim
        h = self._rms_norm(x, layer_params["attn_norm"], cfg.norm_eps)
        q = (h @ layer_params["wq"]).reshape(B, S, cfg.n_heads, hd)
        k = (h @ layer_params["wk"]).reshape(B, S, cfg.n_kv_heads, hd)
        v = (h @ layer_params["wv"]).reshape(B, S, cfg.n_kv_heads, hd)
        q = self._apply_rope(q, cos, sin)
        k = self._apply_rope(k, cos, sin)
        attn = self._attention(q, k, v, positions)
        return x + attn.reshape(B, S, cfg.n_heads * hd) @ layer_params["wo"]

    def _ffn_block(
        self, x: jax.Array, layer_params: Dict[str, jax.Array]
    ) -> jax.Array:
        cfg = self.config
        h = self._rms_norm(x, layer_params["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ layer_params["w_gate"])
        up = h @ layer_params["w_up"]
        return x + (gate * up) @ layer_params["w_down"]

    def _layer(
        self, x: jax.Array, layer_params: Dict[str, jax.Array], rope, positions
    ) -> jax.Array:
        mode = self.config.effective_remat_mode
        attn = self._attn_block
        ffn = self._ffn_block
        ckpt = functools.partial(
            jax.checkpoint,
            policy=jax.checkpoint_policies.nothing_saveable,
            prevent_cse=False,
        )
        if mode == "attn":
            attn = ckpt(attn)
        elif mode == "ffn":
            ffn = ckpt(ffn)
        x = attn(x, layer_params, rope, positions)
        return ffn(x, layer_params)

    def apply(self, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
        """tokens [B, S] → logits [B, S, vocab] (fp32)."""
        cfg = self.config
        B, S = tokens.shape
        x = params["embed"][tokens].astype(cfg.dtype)

        # Shapes under jit are GLOBAL even when the sequence dim is sharded
        # over sp — only the ring-attention shard_map body sees local blocks.
        positions = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
        rope = self._rope(positions)

        def scan_body(carry, layer_params):
            return self._layer(carry, layer_params, rope, positions), None

        if cfg.effective_remat_mode == "layer":
            # keep only the residual stream at layer boundaries; each layer
            # recomputes in the backward pass
            # prevent_cse is unnecessary under lax.scan (per jax docs) and
            # its optimization barriers cost step time
            scan_body = jax.checkpoint(
                scan_body,
                policy=jax.checkpoint_policies.nothing_saveable,
                prevent_cse=False,
            )

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
        x = self._rms_norm(x, params["final_norm"], cfg.norm_eps)
        return (x @ params["lm_head"]).astype(jnp.float32)

    def loss(
        self, params: Dict[str, Any], batch: Tuple[jax.Array, jax.Array]
    ) -> jax.Array:
        """Mean next-token cross-entropy; batch = (tokens, targets)."""
        tokens, targets = batch
        logits = self.apply(params, tokens)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    def _attn_params_per_layer(self) -> int:
        cfg = self.config
        hd = cfg.head_dim
        return (
            cfg.dim * cfg.n_heads * hd  # wq
            + 2 * cfg.dim * cfg.n_kv_heads * hd  # wk, wv
            + cfg.n_heads * hd * cfg.dim  # wo
            + 2 * cfg.dim  # norms
        )

    def _embed_params(self) -> int:
        cfg = self.config
        return cfg.vocab_size * cfg.dim * 2 + cfg.dim  # embed + lm_head + final norm

    def num_params(self) -> int:
        cfg = self.config
        per_layer = self._attn_params_per_layer() + 3 * cfg.dim * cfg.ffn_hidden
        return self._embed_params() + cfg.n_layers * per_layer
