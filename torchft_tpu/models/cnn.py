"""Toy CNN for CIFAR-sized inputs.

The analog of the reference example model (``train_ddp.py:113-135``: a small
conv net used to exercise the FT protocol, not to win benchmarks).  Pure
functional jax: params are a pytree dict, ``apply`` is jit/pjit-friendly
(static shapes, no Python control flow on traced values), convolutions lower
to XLA convs that tile onto the MXU.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class SimpleCNN:
    """conv3x3(32) → conv3x3(64) → maxpool → mlp, NHWC."""

    def __init__(self, num_classes: int = 10, in_channels: int = 3) -> None:
        self.num_classes = num_classes
        self.in_channels = in_channels

    def init(self, key: jax.Array, image_hw: Tuple[int, int] = (32, 32)) -> Dict[str, Any]:
        k1, k2, k3, k4 = jax.random.split(key, 4)
        h, w = image_hw
        flat = (h // 4) * (w // 4) * 64

        def _he(k, shape, fan_in):
            return jax.random.normal(k, shape, dtype=jnp.float32) * np.sqrt(2.0 / fan_in)

        return {
            "conv1": {
                "w": _he(k1, (3, 3, self.in_channels, 32), 9 * self.in_channels),
                "b": jnp.zeros(32),
            },
            "conv2": {"w": _he(k2, (3, 3, 32, 64), 9 * 32), "b": jnp.zeros(64)},
            "fc1": {"w": _he(k3, (flat, 128), flat), "b": jnp.zeros(128)},
            "fc2": {"w": _he(k4, (128, self.num_classes), 128), "b": jnp.zeros(self.num_classes)},
        }

    @staticmethod
    def apply(params: Dict[str, Any], x: jax.Array) -> jax.Array:
        """x: [N, H, W, C] → logits [N, num_classes]."""

        def conv(p, x):
            out = jax.lax.conv_general_dilated(
                x,
                p["w"],
                window_strides=(1, 1),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            return out + p["b"]

        x = jax.nn.relu(conv(params["conv1"], x))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
        x = jax.nn.relu(conv(params["conv2"], x))
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
        x = x.reshape(x.shape[0], -1)
        x = jax.nn.relu(x @ params["fc1"]["w"] + params["fc1"]["b"])
        return x @ params["fc2"]["w"] + params["fc2"]["b"]

    @staticmethod
    def loss(params: Dict[str, Any], batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
        x, y = batch
        logits = SimpleCNN.apply(params, x)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    @staticmethod
    def accuracy(params: Dict[str, Any], batch: Tuple[jax.Array, jax.Array]) -> jax.Array:
        x, y = batch
        return jnp.mean(jnp.argmax(SimpleCNN.apply(params, x), axis=1) == y)
