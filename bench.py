"""Benchmark: the north-star measurement (BASELINE.json).

Three phases, all on the same backend (TPU when the tunnel is healthy):

A. **ws=1 overhead** — tokens/sec/chip for a plain jitted train loop vs the
   full fault-tolerant stack (lighthouse + manager + per-step quorum/commit
   RPCs) in one process.  Gives the absolute tokens/sec/chip number and the
   protocol-overhead ratio.
B. **fault-free fleet** — 2 replica-group subprocesses, each a real
   TCPCommunicator + Manager + HTTP-heal stack doing replica-dim gradient
   averaging over the DCN ring, no failures.  Survivor steps/sec is the
   fault-free fleet baseline.
C. **fleet under faults** — same fleet, but replica 1 is SIGKILLed every K
   survivor steps and auto-respawned (torchft_tpu.launcher supervision); the
   rejoining process heals live weights from the survivor.  Reports the
   with-faults/fault-free throughput ratio (the BASELINE ≥0.95 target) and
   the mean heal-in steps (survivor steps from kill to the victim's first
   committed step back in quorum) — the reference measures the same two
   quantities in its manager integration harness
   (``torchft/manager_integ_test.py:340-430``).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
``value`` is the phase-C/phase-B ratio when the fleet phases complete, else
the phase-A ratio (and "faults" reports why).

Env knobs: TPUFT_BENCH_STEPS, TPUFT_BENCH_DIM, TPUFT_BENCH_LAYERS,
TPUFT_BENCH_SEQ, TPUFT_BENCH_BATCH, TPUFT_BENCH_PLATFORM,
TPUFT_BENCH_FLEET_STEPS, TPUFT_BENCH_KILL_EVERY, TPUFT_BENCH_SKIP_FLEET.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".jax_cache")


def _probe_backend(timeout_s: float = 180.0) -> bool:
    """Check (in a subprocess, so a wedged TPU tunnel can't hang us) that
    the default jax backend can actually initialize."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _configure_jax(platform: Optional[str]) -> None:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    # persistent compile cache: bench reruns (and respawned fleet workers)
    # skip the slow first compile
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _sizes(on_cpu: bool) -> Dict[str, int]:
    """Workload dims; CPU fallback shrinks so the ratio still gets measured
    in minutes rather than timing out the driver."""
    return {
        # phase A sizes a model big enough that a step is tens of ms (like
        # the 8B target scaled to one chip) — against a ~3 ms toy step the
        # fixed ~1 ms/step protocol RPC would read as a 20%+ tax that no
        # real workload sees
        # 40 steps amortize the one D2H sync RTT (~70 ms on the tunnel) to
        # ~2% of the timed window
        "steps": int(os.environ.get("TPUFT_BENCH_STEPS", 10 if on_cpu else 40)),
        "dim": int(os.environ.get("TPUFT_BENCH_DIM", 256 if on_cpu else 768)),
        "layers": int(os.environ.get("TPUFT_BENCH_LAYERS", 4 if on_cpu else 12)),
        "seq": int(os.environ.get("TPUFT_BENCH_SEQ", 256 if on_cpu else 1024)),
        "batch": int(os.environ.get("TPUFT_BENCH_BATCH", 4 if on_cpu else 8)),
        "fleet_steps": int(
            os.environ.get("TPUFT_BENCH_FLEET_STEPS", 16 if on_cpu else 90)
        ),
        "kill_every": int(
            os.environ.get("TPUFT_BENCH_KILL_EVERY", 6 if on_cpu else 30)
        ),
        # fleet phases measure the FT mechanics (quorum, DCN ring, kill,
        # heal); a smaller model keeps per-step host<->device traffic sane —
        # under the axon debug tunnel every D2H crosses a network link, so
        # fleet grads are sized to keep a step in the seconds, not tens
        "fleet_dim": int(
            os.environ.get("TPUFT_BENCH_FLEET_DIM", 256 if on_cpu else 256)
        ),
        "fleet_layers": int(
            os.environ.get("TPUFT_BENCH_FLEET_LAYERS", 4 if on_cpu else 4)
        ),
        "fleet_seq": int(
            os.environ.get("TPUFT_BENCH_FLEET_SEQ", 256 if on_cpu else 512)
        ),
        "fleet_batch": int(
            os.environ.get("TPUFT_BENCH_FLEET_BATCH", 4 if on_cpu else 8)
        ),
    }


def _sync(tree: Any) -> None:
    """True device sync: fetch ONE scalar to host.  Under the axon tunnel
    ``jax.block_until_ready`` acknowledges dispatch without waiting for
    completion — host-side timings read ~0 ms for multi-ms steps — so the
    only honest fence is a D2H readback (costs one ~RTT, amortized across
    the timed loop)."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    jax.device_get(leaf.ravel()[0])


def _build_model(sizes: Dict[str, int]):
    import jax.numpy as jnp

    from torchft_tpu.models.llama import Llama, LlamaConfig

    config = LlamaConfig(
        vocab_size=8192,
        dim=sizes["dim"],
        n_layers=sizes["layers"],
        n_heads=max(1, sizes["dim"] // 64),
        n_kv_heads=max(1, sizes["dim"] // 128),
        ffn_hidden=sizes["dim"] * 3,
        max_seq_len=sizes["seq"],
        dtype=jnp.bfloat16,
    )
    return Llama(config), config


# --------------------------------------------------------------------------
# fleet worker (subprocess entry: `python bench.py --worker`)
# --------------------------------------------------------------------------


def worker_main() -> None:
    _configure_jax(os.environ.get("TPUFT_BENCH_WORKER_PLATFORM") or None)

    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu.communicator import TCPCommunicator
    from torchft_tpu.ddp import ft_allreduce
    from torchft_tpu.manager import Manager
    from torchft_tpu.optim import OptimizerWrapper

    rg = int(os.environ["REPLICA_GROUP_ID"])
    target = int(os.environ["TPUFT_BENCH_TARGET_STEPS"])
    events_dir = os.environ["TPUFT_BENCH_EVENTS_DIR"]
    events_path = os.path.join(events_dir, f"replica_{rg}.jsonl")
    stop_path = os.path.join(events_dir, "stop")
    sizes = {
        k: int(os.environ[f"TPUFT_BENCH_{k.upper()}"])
        for k in ("dim", "layers", "seq", "batch")
    }
    sizes["steps"] = target

    model, config = _build_model(sizes)
    device = jax.devices()[0]
    # identical init on every replica (the reference seeds identically in its
    # examples; init_sync covers the general case)
    params = jax.device_put(model.init(jax.random.PRNGKey(0)), device)
    tx = optax.adamw(1e-3)
    holder = {"params": params, "opt_state": jax.jit(tx.init)(params)}

    # distinct per-replica data so the replica-dim average does real work
    key = jax.random.PRNGKey(1000 + rg)
    batches = []
    for i in range(4):
        k = jax.random.fold_in(key, i)
        tokens = jax.random.randint(
            k, (sizes["batch"], sizes["seq"]), 0, config.vocab_size
        )
        batches.append(
            (jax.device_put(tokens, device), jnp.roll(tokens, -1, axis=1))
        )

    manager = Manager(
        comm=TCPCommunicator(timeout_s=30.0),
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=1,
        replica_id=f"bench_{rg}",
    )
    opt = OptimizerWrapper(manager, tx)
    grad_step = jax.jit(jax.value_and_grad(model.loss))

    # the parent ends the phase via the stop file (so a healing victim gets
    # to rejoin even after the survivor passed the measurement target);
    # the hard cap is a runaway backstop
    with open(events_path, "a", buffering=1) as ev:
        while (
            not os.path.exists(stop_path)
            and manager.current_step() < target * 5
        ):
            opt.start_step()
            batch = batches[manager.current_step() % len(batches)]
            loss, grads = grad_step(holder["params"], batch)
            grads = ft_allreduce(manager, grads)
            if opt.step(holder, grads):
                ev.write(
                    json.dumps(
                        {"step": manager.current_step(), "ts": time.time()}
                    )
                    + "\n"
                )
    manager.shutdown()


# --------------------------------------------------------------------------
# fleet orchestration (phases B and C)
# --------------------------------------------------------------------------


def _read_events(events_dir: str, rg: int) -> List[Tuple[int, float]]:
    path = os.path.join(events_dir, f"replica_{rg}.jsonl")
    out: List[Tuple[int, float]] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                    out.append((rec["step"], rec["ts"]))
                except (json.JSONDecodeError, KeyError):
                    continue  # torn final line of a SIGKILLed writer
    except FileNotFoundError:
        pass
    return out


def run_fleet(
    label: str,
    target_steps: int,
    sizes: Dict[str, int],
    worker_platform: Optional[str],
    kill_every: int = 0,
    replicas: int = 2,
    deadline_s: float = 360.0,
) -> Dict[str, Any]:
    """Run a fleet of replica-group subprocesses to ``target_steps``; if
    ``kill_every`` > 0, SIGKILL replica 1 every ``kill_every`` survivor
    steps (once the victim has rejoined).  Returns throughput + heal stats
    computed from the per-replica committed-step event logs."""
    from torchft_tpu.launcher import ReplicaSpec, ReplicaSupervisor
    from torchft_tpu.lighthouse import LighthouseServer

    events_dir = tempfile.mkdtemp(prefix=f"tpuft_bench_{label}_")
    lighthouse = LighthouseServer(
        bind="127.0.0.1:0",
        min_replicas=1,
        join_timeout_ms=3000,
        quorum_tick_ms=50,
    )
    env = {
        "TPUFT_BENCH_EVENTS_DIR": events_dir,
        "TPUFT_BENCH_TARGET_STEPS": str(target_steps),
        "TPUFT_BENCH_WORKER_PLATFORM": worker_platform or "",
    }
    for k in ("dim", "layers", "seq", "batch"):
        env[f"TPUFT_BENCH_{k.upper()}"] = str(sizes[f"fleet_{k}"])
    specs = [
        ReplicaSpec(
            replica_group_id=i,
            cmd=[sys.executable, os.path.abspath(__file__), "--worker"],
            env=dict(env),
        )
        for i in range(replicas)
    ]
    supervisor = ReplicaSupervisor(
        specs,
        f"127.0.0.1:{lighthouse.port}",
        restart_delay_s=0.5,
    )
    runner = threading.Thread(target=supervisor.run, daemon=True)
    runner.start()

    kills: List[Dict[str, Any]] = []
    next_kill = kill_every
    deadline = time.time() + deadline_s
    heal_grace_s = 90.0
    stop_path = os.path.join(events_dir, "stop")
    try:
        while time.time() < deadline:
            ev0 = _read_events(events_dir, 0)
            ev1 = _read_events(events_dir, 1)
            # victim counts as (re)joined once it has committed a step since
            # the last kill (or at all, before the first kill)
            victim_back = bool(ev1) and (
                not kills or ev1[-1][1] > kills[-1]["ts"]
            )
            if ev0 and ev0[-1][0] >= target_steps:
                # survivor hit the measurement target; linger (bounded) so a
                # mid-heal victim gets to rejoin — that rejoin is the
                # heal-in data point
                if (
                    not kills
                    or victim_back
                    or time.time() - kills[-1]["ts"] > heal_grace_s
                ):
                    break
            elif (
                kill_every
                and ev0
                and ev0[-1][0] >= next_kill
                and victim_back
                and supervisor.kill(1)
            ):
                # only re-kill once the victim has rejoined (committed a step
                # since the last kill), so each heal-in is well defined
                kills.append({"ts": time.time(), "survivor_step": ev0[-1][0]})
                print(
                    f"bench[{label}]: killed replica 1 at survivor "
                    f"step {ev0[-1][0]}",
                    file=sys.stderr,
                )
                next_kill = ev0[-1][0] + kill_every
            time.sleep(0.25)
    finally:
        with open(stop_path, "w") as f:
            f.write("stop")
        runner.join(timeout=60)
        supervisor.stop()
        lighthouse.shutdown()

    ev0 = _read_events(events_dir, 0)
    ev1 = _read_events(events_dir, 1)
    return _fleet_metrics(label, target_steps, ev0, ev1, kills)


def _fleet_metrics(
    label: str,
    target_steps: int,
    ev0: List[Tuple[int, float]],
    ev1: List[Tuple[int, float]],
    kills: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Throughput + heal statistics from the committed-step event logs.

    Both replica processes share one physical chip in this harness, so the
    survivor literally speeds up while its peer is dead (decontention) — a
    raw with-faults/fault-free wall-clock ratio would overstate fault
    tolerance.  Instead the fault cost is measured directly: the survivor's
    steady-state step time during both-alive periods (``t_step_s``) vs the
    extra time its disrupted steps took around each kill and each rejoin
    (``overhead_per_kill_s``).  BASELINE's fault rate is one kill per 100
    steps, so the north-star ratio is ``100·t / (100·t + overhead)``.
    """
    result: Dict[str, Any] = {
        "label": label,
        "kills": len(kills),
        "survivor_steps": ev0[-1][0] if ev0 else 0,
        "completed": bool(ev0 and ev0[-1][0] >= target_steps),
    }
    if len(ev0) < 2:
        return result

    # per-step durations for the survivor: dts[i] = time to commit ev0[i]
    dts = [
        (ev0[i][0], ev0[i][1], ev0[i][1] - ev0[i - 1][1])
        for i in range(1, len(ev0))
    ]

    # both-alive steady state: steps committed while the victim was live
    # (between its rejoin and the next kill), excluding 2 warmup steps after
    # each (re)join
    def _victim_alive(ts: float) -> bool:
        if not ev1:
            return False
        alive = False
        # victim is alive from each of its events until the next kill
        last_kill = None
        for kill in kills:
            if kill["ts"] <= ts:
                last_kill = kill["ts"]
        evs_before = [t for (_s, t) in ev1 if t <= ts]
        if not evs_before:
            return False
        if last_kill is None:
            return True
        return max(evs_before) > last_kill

    steady = [dt for (_s, ts, dt) in dts if _victim_alive(ts)]
    # skip the slowest tail (rejoin warmup / heal pauses land inside
    # both-alive windows); median is robust to them
    if steady:
        steady_sorted = sorted(steady)
        t_step = steady_sorted[len(steady_sorted) // 2]
        result["t_step_s"] = round(t_step, 4)
        result["survivor_steps_per_sec"] = round(1.0 / t_step, 3)
    else:
        t_step = None

    # wall-clock throughput over the whole phase (raw, contention-skewed)
    span_steps = ev0[-1][0] - ev0[0][0]
    span_time = ev0[-1][1] - ev0[0][1]
    if span_steps > 0 and span_time > 0:
        result["survivor_steps_per_sec_raw"] = round(span_steps / span_time, 3)

    # per-kill disruption: extra time (beyond steady t_step) of survivor
    # steps from the kill until 3 steps after the victim's first committed
    # step back (covers the failed step, both reconfigures, and the heal
    # pause); heal-in = survivor steps the victim missed
    heal_ins: List[int] = []
    heal_secs: List[float] = []
    overheads: List[float] = []
    for kill in kills:
        back = [(s, t) for (s, t) in ev1 if t > kill["ts"]]
        rejoin_ts = back[0][1] if back else None
        if rejoin_ts is not None:
            survivor_at_rejoin = max(
                (s for (s, t) in ev0 if t <= rejoin_ts),
                default=kill["survivor_step"],
            )
            heal_ins.append(max(0, survivor_at_rejoin - kill["survivor_step"]))
            heal_secs.append(rejoin_ts - kill["ts"])
        if t_step is not None:
            if rejoin_ts is not None:
                window_end = rejoin_ts + 3 * t_step
            else:
                window_end = kill["ts"] + 10 * t_step
            dis = [
                dt
                for (_s, ts, dt) in dts
                if kill["ts"] <= ts <= window_end
            ]
            overheads.append(sum(max(0.0, dt - t_step) for dt in dis))
    if heal_ins:
        # heal-in in steps scales with the survivor's step time; seconds is
        # the environment-independent number (process respawn + jax init +
        # rejoin + heal transfer)
        result["mean_heal_in_steps"] = round(sum(heal_ins) / len(heal_ins), 1)
        result["mean_heal_in_s"] = round(sum(heal_secs) / len(heal_secs), 1)
        result["heal_ins"] = heal_ins
    if overheads:
        result["overhead_per_kill_s"] = round(
            sum(overheads) / len(overheads), 3
        )
        if t_step:
            per100 = 100.0 * t_step
            result["ratio_per_100step_kill"] = round(
                per100 / (per100 + result["overhead_per_kill_s"]), 4
            )
    return result


# --------------------------------------------------------------------------
# phase A: single-chip ws=1 overhead + absolute tokens/sec/chip
# --------------------------------------------------------------------------


def run_single(sizes: Dict[str, int]) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu.communicator import TCPCommunicator
    from torchft_tpu.ddp import ft_allreduce
    from torchft_tpu.lighthouse import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.optim import OptimizerWrapper

    steps = sizes["steps"]
    model, config = _build_model(sizes)
    device = jax.devices()[0]
    print(
        f"bench: llama dim={sizes['dim']} layers={sizes['layers']} "
        f"seq={sizes['seq']} batch={sizes['batch']} "
        f"params={model.num_params()/1e6:.1f}M on {device.platform}",
        file=sys.stderr,
    )

    params = jax.device_put(model.init(jax.random.PRNGKey(0)), device)
    tx = optax.adamw(1e-3)
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(
        key, (sizes["batch"], sizes["seq"]), 0, config.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    batch_data = (jax.device_put(tokens, device), jax.device_put(targets, device))
    tokens_per_step = sizes["batch"] * sizes["seq"]

    grad_step = jax.jit(jax.value_and_grad(model.loss))

    def update_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    update_step = jax.jit(update_fn, donate_argnums=(0, 1))

    # fault-free baseline.  deep copy: update_step donates its inputs, and
    # the FT phase below must not read donated buffers
    ff_params = jax.tree_util.tree_map(jnp.copy, params)
    opt_state = jax.jit(tx.init)(ff_params)
    # several warmup steps: the first post-compile iterations can run slow
    # (autotuning/tunnel warm-up) and would skew a 20-step measurement
    for _ in range(4):
        loss, grads = grad_step(ff_params, batch_data)
        ff_params, opt_state = update_step(ff_params, opt_state, grads)
    _sync(ff_params)

    start = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_step(ff_params, batch_data)
        ff_params, opt_state = update_step(ff_params, opt_state, grads)
    _sync(ff_params)
    faultfree_s = (time.perf_counter() - start) / steps
    faultfree_tps = tokens_per_step / faultfree_s
    print(
        f"fault-free: {faultfree_s*1e3:.1f} ms/step, {faultfree_tps:,.0f} tok/s",
        file=sys.stderr,
    )

    # full FT stack, ws=1
    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=50, quorum_tick_ms=20
    )
    holder = {"params": params, "opt_state": jax.jit(tx.init)(params)}
    manager = Manager(
        comm=TCPCommunicator(timeout_s=60.0),
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=1,
        replica_id="bench_0",
        lighthouse_addr=lighthouse.local_address(),
    )
    opt = OptimizerWrapper(manager, tx)

    def ft_step() -> None:
        opt.start_step()
        loss, grads = grad_step(holder["params"], batch_data)
        grads = ft_allreduce(manager, grads)
        opt.step(holder, grads)

    for _ in range(4):  # warm the protocol path + post-compile iterations
        ft_step()
    _sync(holder["params"])

    start = time.perf_counter()
    for _ in range(steps):
        ft_step()
    _sync(holder["params"])
    ft_s = (time.perf_counter() - start) / steps
    ft_tps = tokens_per_step / ft_s
    print(f"ft: {ft_s*1e3:.1f} ms/step, {ft_tps:,.0f} tok/s", file=sys.stderr)

    manager.shutdown()
    lighthouse.shutdown()

    # achieved model FLOPs: the standard 6N per token for the train step
    # (fwd+bwd) plus the attention score/value matmuls 12·L·dim·S.  N
    # excludes the embedding table (a gather, not a matmul — PaLM MFU
    # convention) but keeps the lm_head projection, which is a real matmul
    matmul_params = model.num_params() - config.vocab_size * config.dim
    flops_per_token = 6 * matmul_params + 12 * sizes["layers"] * sizes[
        "dim"
    ] * sizes["seq"]
    tflops = ft_tps * flops_per_token / 1e12
    out = {
        "faultfree_tokens_per_sec": round(faultfree_tps, 1),
        "ft_tokens_per_sec": round(ft_tps, 1),
        "ws1_ratio": round(ft_tps / faultfree_tps, 4),
        "model_tflops_per_sec": round(tflops, 2),
        "platform": device.platform,
    }
    peak = os.environ.get("TPUFT_PEAK_TFLOPS")
    if peak:
        out["mfu"] = round(tflops / float(peak), 4)
    print(
        f"bench: {tflops:.2f} model TFLOP/s achieved (ft path)",
        file=sys.stderr,
    )
    return out


def main() -> None:
    platform = os.environ.get("TPUFT_BENCH_PLATFORM")
    if not platform and not _probe_backend():
        print(
            "bench: default backend failed to initialize (wedged TPU tunnel?); "
            "falling back to cpu",
            file=sys.stderr,
        )
        platform = "cpu"
    _configure_jax(platform)

    import jax

    on_cpu = jax.default_backend() == "cpu"
    sizes = _sizes(on_cpu)

    single = run_single(sizes)

    faults: Dict[str, Any] = {}
    ratio = None
    if not os.environ.get("TPUFT_BENCH_SKIP_FLEET"):
        worker_platform = "cpu" if on_cpu else None
        faultfree = run_fleet(
            "faultfree",
            target_steps=max(10, sizes["fleet_steps"] // 3),
            sizes=sizes,
            worker_platform=worker_platform,
        )
        print(f"bench: fleet fault-free {faultfree}", file=sys.stderr)
        faulted = run_fleet(
            "faults",
            target_steps=sizes["fleet_steps"],
            sizes=sizes,
            worker_platform=worker_platform,
            kill_every=sizes["kill_every"],
        )
        print(f"bench: fleet with faults {faulted}", file=sys.stderr)
        faults = {
            "fleet_steps": sizes["fleet_steps"],
            "kill_every": sizes["kill_every"],
            "kills": faulted.get("kills", 0),
            "faultfree_fleet": faultfree,
            "faulted_fleet": faulted,
        }
        if faulted.get("mean_heal_in_steps") is not None:
            faults["mean_heal_in_steps"] = faulted["mean_heal_in_steps"]
        if faulted.get("mean_heal_in_s") is not None:
            faults["mean_heal_in_s"] = faulted["mean_heal_in_s"]
        ratio = faulted.get("ratio_per_100step_kill")

    if ratio is None:
        # fleet phases unusable: fall back to the ws=1 protocol ratio so the
        # bench always reports something honest
        ratio = single["ws1_ratio"]
        faults.setdefault("note", "fleet phases incomplete; value is ws=1 ratio")
        metric = "ft_vs_faultfree_tokens_per_sec_ratio"
    else:
        # BASELINE's contract: sustained throughput under one replica kill
        # per 100 steps, measured from the survivor's steady step time and
        # the per-kill disruption overhead (see _fleet_metrics)
        metric = "ft_withfaults_vs_faultfree_tokens_per_sec_ratio_100step_kill"

    out = {
        "metric": metric,
        "value": round(ratio, 4),
        "unit": "ratio",
        "vs_baseline": round(ratio / 0.95, 4),
        **single,
    }
    if faults:
        out["faults"] = faults
        if "mean_heal_in_steps" in faults:
            out["mean_heal_in_steps"] = round(faults["mean_heal_in_steps"], 1)
    print(json.dumps(out))


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker_main()
    else:
        main()
