"""Benchmark: fault-tolerance overhead on the flagship model.

Measures tokens/sec/chip for (a) a plain jitted train loop and (b) the full
fault-tolerant stack — in-process lighthouse + manager server + per-step
quorum/commit RPCs + host-side replica-dim gradient averaging — on the same
chip, and reports the FT/fault-free throughput ratio.  The north-star target
(BASELINE.json) is sustaining ≥95% of fault-free throughput, so
``vs_baseline = ratio / 0.95`` (≥1 is at/above target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Env knobs: TPUFT_BENCH_STEPS, TPUFT_BENCH_DIM, TPUFT_BENCH_LAYERS,
TPUFT_BENCH_SEQ, TPUFT_BENCH_BATCH, TPUFT_BENCH_PLATFORM.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def _probe_backend(timeout_s: float = 180.0) -> bool:
    """Check (in a subprocess, so a wedged TPU tunnel can't hang us) that
    the default jax backend can actually initialize."""
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return probe.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    platform = os.environ.get("TPUFT_BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)
    elif not _probe_backend():
        print(
            "bench: default backend failed to initialize (wedged TPU tunnel?); "
            "falling back to cpu",
            file=sys.stderr,
        )
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: bench reruns skip the slow first compile
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import optax

    from torchft_tpu.communicator import TCPCommunicator
    from torchft_tpu.ddp import ft_allreduce
    from torchft_tpu.lighthouse import LighthouseServer
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.llama import Llama, LlamaConfig
    from torchft_tpu.optim import OptimizerWrapper

    on_cpu = jax.default_backend() == "cpu"
    # CPU fallback shrinks the workload so the ratio still gets measured in
    # minutes rather than timing out the driver
    steps = int(os.environ.get("TPUFT_BENCH_STEPS", 10 if on_cpu else 20))
    dim = int(os.environ.get("TPUFT_BENCH_DIM", 256 if on_cpu else 512))
    layers = int(os.environ.get("TPUFT_BENCH_LAYERS", 4 if on_cpu else 8))
    seq = int(os.environ.get("TPUFT_BENCH_SEQ", 256 if on_cpu else 1024))
    batch = int(os.environ.get("TPUFT_BENCH_BATCH", 4 if on_cpu else 8))

    config = LlamaConfig(
        vocab_size=8192,
        dim=dim,
        n_layers=layers,
        n_heads=max(1, dim // 64),
        n_kv_heads=max(1, dim // 128),
        ffn_hidden=dim * 3,
        max_seq_len=seq,
        dtype=jnp.bfloat16,
    )
    model = Llama(config)
    device = jax.devices()[0]
    print(
        f"bench: llama dim={dim} layers={layers} seq={seq} batch={batch} "
        f"params={model.num_params()/1e6:.1f}M on {device.platform}",
        file=sys.stderr,
    )

    params = jax.device_put(model.init(jax.random.PRNGKey(0)), device)
    tx = optax.adamw(1e-3)
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(key, (batch, seq), 0, config.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    batch_data = (jax.device_put(tokens, device), jax.device_put(targets, device))
    tokens_per_step = batch * seq

    grad_step = jax.jit(jax.value_and_grad(model.loss))

    def update_fn(params, opt_state, grads):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    update_step = jax.jit(update_fn, donate_argnums=(0, 1))

    # ---------------- fault-free baseline ----------------
    # deep copy: update_step donates its inputs, and the FT phase below must
    # not read donated buffers
    ff_params = jax.tree_util.tree_map(jnp.copy, params)
    opt_state = jax.jit(tx.init)(ff_params)
    loss, grads = grad_step(ff_params, batch_data)  # compile
    ff_params, opt_state = update_step(ff_params, opt_state, grads)
    jax.block_until_ready(ff_params)

    start = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_step(ff_params, batch_data)
        ff_params, opt_state = update_step(ff_params, opt_state, grads)
    jax.block_until_ready(ff_params)
    faultfree_s = (time.perf_counter() - start) / steps
    faultfree_tps = tokens_per_step / faultfree_s
    print(f"fault-free: {faultfree_s*1e3:.1f} ms/step, {faultfree_tps:,.0f} tok/s", file=sys.stderr)

    # ---------------- full FT stack ----------------
    lighthouse = LighthouseServer(
        bind="127.0.0.1:0", min_replicas=1, join_timeout_ms=50, quorum_tick_ms=20
    )
    holder = {"params": params, "opt_state": jax.jit(tx.init)(params)}
    manager = Manager(
        comm=TCPCommunicator(timeout_s=60.0),
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=1,
        replica_id="bench_0",
        lighthouse_addr=lighthouse.local_address(),
    )
    opt = OptimizerWrapper(manager, tx)

    def ft_step() -> None:
        opt.start_step()
        loss, grads = grad_step(holder["params"], batch_data)
        grads = ft_allreduce(manager, grads)
        opt.step(holder, grads)

    ft_step()  # warm the protocol path
    jax.block_until_ready(holder["params"])

    start = time.perf_counter()
    for _ in range(steps):
        ft_step()
    jax.block_until_ready(holder["params"])
    ft_s = (time.perf_counter() - start) / steps
    ft_tps = tokens_per_step / ft_s
    print(f"ft: {ft_s*1e3:.1f} ms/step, {ft_tps:,.0f} tok/s", file=sys.stderr)

    manager.shutdown()
    lighthouse.shutdown()

    ratio = ft_tps / faultfree_tps
    print(
        json.dumps(
            {
                "metric": "ft_vs_faultfree_tokens_per_sec_ratio",
                "value": round(ratio, 4),
                "unit": "ratio",
                "vs_baseline": round(ratio / 0.95, 4),
                "faultfree_tokens_per_sec": round(faultfree_tps, 1),
                "ft_tokens_per_sec": round(ft_tps, 1),
                "platform": device.platform,
            }
        )
    )


if __name__ == "__main__":
    main()
