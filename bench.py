"""Benchmark: the north-star measurement (BASELINE.json).

Four phases, all on the same backend (TPU when the tunnel is healthy):

A. **ws=1 overhead + MFU** — tokens/sec/chip for the plain train step
   (ALL measured steps scan-chained inside ONE jit: the honest
   peak-compute number under the axon tunnel, and what MFU is computed
   from) vs the full fault-tolerant stack (lighthouse + manager +
   per-step quorum/commit RPCs, a python step loop by design) in one
   process, on a ~0.8B-param Llama with the cheapest remat policy that
   fits (attn → ffn → layer OOM walk).  Reports absolute tokens/sec/chip,
   model TFLOP/s, and MFU against the chip's autodetected bf16 peak.
B. **fault-free fleet** — N replica-group subprocesses (default 3 on TPU),
   each a real Communicator + Manager + HTTP-heal stack doing replica-dim
   gradient averaging over the DCN ring, no failures.
C. **fleet under faults** — same fleet, but victims (rotating over replicas
   1..N-1; replica 0 is the measurement anchor) are SIGKILLed every K
   survivor steps and auto-respawned; each rejoining process heals live
   weights from a survivor.  Reports the with-faults/fault-free throughput
   ratio (the BASELINE >=0.95 target), mean heal-in seconds, and a
   per-phase **heal breakdown** (respawn / jax init / model build / join+
   rendezvous+transfer / first-step compile) from worker-side phase logs.
   The reference measures the same quantities in its manager integration
   harness (``torchft/manager_integ_test.py:340-430``).
D. **DiLoCo under churn** (BASELINE config 4) — N islands running
   Streaming DiLoCo (fragments, sync_every, τ delay) with kills timed to
   land inside the fragment-sync window; reports inner-step throughput
   ratio vs a fault-free DiLoCo fleet and the per-sync overhead
   (``torchft/local_sgd.py:175-795``).

The whole bench runs on the production tier by default: C++ lighthouse +
manager servers and the C++ data-plane communicator when
``native/libtpuft.so`` loads, Python otherwise (``"tier"`` in the output
records which; the reference likewise benches NCCL, not Gloo).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
``value`` is the phase-C/phase-B ratio when the fleet phases complete, else
the phase-A ratio (and "faults" reports why).

Env knobs: TPUFT_BENCH_STEPS, TPUFT_BENCH_DIM, TPUFT_BENCH_LAYERS,
TPUFT_BENCH_SEQ, TPUFT_BENCH_BATCH, TPUFT_BENCH_HEAD_DIM,
TPUFT_BENCH_REMAT, TPUFT_BENCH_PLATFORM, TPUFT_BENCH_FLEET_STEPS,
TPUFT_BENCH_KILL_EVERY, TPUFT_BENCH_REPLICAS, TPUFT_BENCH_SKIP_FLEET,
TPUFT_BENCH_SKIP_DILOCO, TPUFT_BENCH_DILOCO_QUANT (0/1/auto),
TPUFT_BENCH_OUT (streaming artifact path), TPUFT_BENCH_REPROBE_WINDOW_S /
TPUFT_BENCH_REPROBE_BUDGET_S (mid-run TPU recovery),
TPUFT_BENCH_TOTAL_BUDGET_S (wall-clock bound incl. the initial probe;
phases shrink/skip to fit — except a wedged-tunnel probe only eats the
budget down to TPUFT_BENCH_PHASE_FLOOR_S.  Per-fleet deadline floors
(120/180 s, DiLoCo 90/180 s) are capped at what remains once the budget
is spent, so the hard worst case a driver must allow before hard-killing
is probe window + probe timeout + phase floor + the one fleet floor that
straddles the deadline (<= 180 s) + teardown),
TPUFT_BENCH_HEAL_TRANSPORT (comm|http — heal over the collective fabric
vs the reference-parity HTTP server), TPUFT_PEAK_TFLOPS, TORCHFT_TIER.

Output contract: stdout's LAST line is one compact headline JSON (<=~1 KB,
survives a 2000-char tail capture); the full nested artifact streams to
``bench_out.json`` (or TPUFT_BENCH_OUT) as each phase completes.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

CACHE_DIR = os.path.join(REPO, ".jax_cache")

# per-chip bf16 peak TFLOP/s by device_kind substring (first match wins;
# "lite" variants must precede the bare generation string)
_TPU_PEAKS: List[Tuple[str, float]] = [
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v5litepod", 197.0),
    ("v5p", 459.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
]


def _peak_tflops(device) -> Optional[float]:
    env = os.environ.get("TPUFT_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = (getattr(device, "device_kind", "") or "").lower()
    for pat, peak in _TPU_PEAKS:
        if pat in kind:
            return peak
    return None


def _probe_backend_with_retries() -> bool:
    """The TPU tunnel wedges *transiently*; a single failed probe must not
    silently downgrade the whole bench to CPU (round 3's artifact lost its
    TPU numbers to exactly that).  Retry within a bounded window, then fall
    back LOUDLY.  The probe itself lives in ``torchft_tpu.utils.probe``
    (shared with ``__graft_entry__``)."""
    from torchft_tpu.utils.probe import backend_executes_with_retries

    return backend_executes_with_retries(
        window_s=float(os.environ.get("TPUFT_BENCH_PROBE_WINDOW_S", "900")),
        timeout_s=float(os.environ.get("TPUFT_BENCH_PROBE_TIMEOUT_S", "180")),
        log=lambda msg: print(f"bench: {msg}", file=sys.stderr),
    )


def _configure_jax(platform: Optional[str]) -> None:
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    # persistent compile cache: bench reruns (and respawned fleet workers)
    # skip the slow first compile
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _sizes(on_cpu: bool) -> Dict[str, int]:
    """Workload dims; CPU fallback shrinks so the ratio still gets measured
    in minutes rather than timing out the driver."""

    def env_int(name: str, cpu: int, tpu: int) -> int:
        return int(os.environ.get(name, cpu if on_cpu else tpu))

    return {
        # phase A: a ~0.8B-param Llama (dim 2048 x 16 layers, head_dim 128,
        # seq 2048) — big enough that MXU efficiency, not protocol RPC,
        # decides the number; remat makes it fit single-chip HBM
        "steps": env_int("TPUFT_BENCH_STEPS", 8, 30),
        "dim": env_int("TPUFT_BENCH_DIM", 256, 2048),
        "layers": env_int("TPUFT_BENCH_LAYERS", 4, 16),
        "seq": env_int("TPUFT_BENCH_SEQ", 256, 2048),
        "batch": env_int("TPUFT_BENCH_BATCH", 4, 8),
        "head_dim": env_int("TPUFT_BENCH_HEAD_DIM", 64, 128),
        "remat": env_int("TPUFT_BENCH_REMAT", 0, 1),
        # CPU-fallback fleet sizes amortize heal cost honestly: at 48 steps
        # and a kill every 14 the per-100-step normalization sees 3 kills
        # (14/28/42 — a 16-step cadence lands the third ON the target step
        # and loses it) averaged over a real steady phase rather than 2
        # kills dominating a 16-step blip (the round-4 artifact's 0.9485)
        "fleet_steps": env_int("TPUFT_BENCH_FLEET_STEPS", 48, 100),
        "kill_every": env_int("TPUFT_BENCH_KILL_EVERY", 14, 25),
        # 3 replicas even on CPU: victim rotation + the cold last victim
        # record BOTH heal paths (standby + cold) in one artifact
        "replicas": env_int("TPUFT_BENCH_REPLICAS", 3, 3),
        # fleet phases measure the FT mechanics (quorum, DCN ring, kill,
        # heal); a smaller model keeps per-step host<->device traffic sane —
        # under the axon debug tunnel every D2H crosses a network link
        "fleet_dim": env_int("TPUFT_BENCH_FLEET_DIM", 256, 256),
        "fleet_layers": env_int("TPUFT_BENCH_FLEET_LAYERS", 4, 4),
        "fleet_seq": env_int("TPUFT_BENCH_FLEET_SEQ", 256, 512),
        "fleet_batch": env_int("TPUFT_BENCH_FLEET_BATCH", 4, 8),
        "fleet_head_dim": 64,
        # warm standby for killable replicas: a parked pre-initialized spare
        # is promoted on kill, cutting heal-in from cold-start seconds to
        # join+transfer seconds (0 measures the cold path instead)
        "standby": env_int("TPUFT_BENCH_STANDBY", 1, 1),
        # phase D (DiLoCo): inner steps + streaming-fragment schedule;
        # >= 3 in-window kills on EVERY platform so the churn ratio is
        # never a sample-of-one (rounds 3+4 shipped single-kill artifacts)
        "diloco_steps": env_int("TPUFT_BENCH_DILOCO_STEPS", 48, 96),
        "diloco_sync_every": env_int("TPUFT_BENCH_DILOCO_SYNC", 8, 8),
        "diloco_fragments": 2,
        "diloco_sync_delay": 2,
        "diloco_kills": env_int("TPUFT_BENCH_DILOCO_KILLS", 3, 3),
    }


def _quant_kind_or_error() -> str:
    """The validated wire kind actually in effect (the workers' Manager
    would reject an invalid one at startup) — never the raw env string."""
    from torchft_tpu.quantization import quant_kind

    try:
        return quant_kind()
    except ValueError as e:
        return f"invalid ({e})"


def _diloco_quant_env() -> str:
    """The DiLoCo quantized-sync knob: "0" / "1" force the wire; the
    default "auto" has phase D measure BOTH fault-free and gate the churn
    run on the one that actually costs less per sync on this link
    (quantization spends host cycles that a fat loopback never pays back —
    the reference keeps it opt-in, ``manager.py:457-468``)."""
    v = os.environ.get("TPUFT_BENCH_DILOCO_QUANT", "auto").strip().lower()
    return v if v in ("0", "1") else "auto"


def _outer_shard_mode_env() -> str:
    """Canonical TORCHFT_OUTER_SHARD mode via the SAME parser the workers
    use (``local_sgd._outer_shard_mode``), so every accepted spelling —
    'off'/'false' included — labels the artifact the way the fleet actually
    ran.  An unparseable value falls back to the raw string: it will never
    equal "0", and the workers crash on it loudly anyway."""
    from torchft_tpu.local_sgd import _outer_shard_mode

    try:
        return _outer_shard_mode()
    except ValueError:
        return os.environ.get("TORCHFT_OUTER_SHARD", "auto").strip().lower()


def _sync(tree: Any) -> None:
    """True device sync: fetch ONE scalar to host.  Under the axon tunnel
    ``jax.block_until_ready`` acknowledges dispatch without waiting for
    completion — host-side timings read ~0 ms for multi-ms steps — so the
    only honest fence is a D2H readback (costs one ~RTT, amortized across
    the timed loop)."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    jax.device_get(leaf.ravel()[0])


def _build_model(
    sizes: Dict[str, int], fleet: bool = False, remat_mode: str = "none"
):
    import jax.numpy as jnp

    from torchft_tpu.models.llama import Llama, LlamaConfig

    prefix = "fleet_" if fleet else ""
    dim = sizes[f"{prefix}dim"]
    head_dim = sizes[f"{prefix}head_dim"]
    n_heads = max(1, dim // head_dim)
    config = LlamaConfig(
        vocab_size=8192,
        dim=dim,
        n_layers=sizes[f"{prefix}layers"],
        n_heads=n_heads,
        n_kv_heads=max(1, n_heads // 4),
        ffn_hidden=dim * 3,
        max_seq_len=sizes[f"{prefix}seq"],
        dtype=jnp.bfloat16,
        remat_mode="none" if fleet else remat_mode,
    )
    return Llama(config), config


# extra hardware FLOPs each remat policy re-runs in the backward, as a
# multiplier on the 6N/token convention (fwd 2N + bwd 4N): "layer" re-runs
# the whole forward (+2N -> 8/6); "ffn" re-runs the FFN forward (~78% of
# the weight-matmul FLOPs at ffn_hidden = 3*dim, GQA/4 -> ~7.56/6);
# "attn" re-runs the attention forward (~22% + scores -> ~6.7/6)
_REMAT_HW_FACTOR = {
    "none": 1.0,
    "attn": 6.7 / 6.0,
    "ffn": 7.56 / 6.0,
    "layer": 8.0 / 6.0,
}


def _phase_a_modes(sizes: Dict[str, int]) -> List[str]:
    """Remat-mode preference for phase A.  Explicit env wins; otherwise,
    when remat is requested, try cheapest-recompute first and let the OOM
    fallback in :func:`run_single` walk toward "layer" — the mode that is
    known to fit.  Recompute tax: attn ~12%, ffn ~26%, layer ~33%."""
    env = os.environ.get("TPUFT_BENCH_REMAT_MODE", "")
    if env:
        return [env]
    if not sizes.get("remat"):
        return ["none"]
    return ["attn", "ffn", "layer"]


# --------------------------------------------------------------------------
# fleet worker (subprocess entry: `python bench.py --worker`)
# --------------------------------------------------------------------------


class _EventLog:
    """Line-buffered JSONL event/phase log; survives SIGKILL mid-line (the
    reader skips torn lines).  Every record carries the writer's pid: a
    replica group's log interleaves multiple process incarnations (active,
    killed, promoted standby, re-warmed spare), and heal attribution must
    only read the incarnation that actually rejoined."""

    def __init__(self, path: str) -> None:
        self._f = open(path, "a", buffering=1)
        self._pid = os.getpid()

    def phase(self, name: str, **extra: Any) -> None:
        rec = {"phase": name, "ts": time.time(), "pid": self._pid}
        rec.update(extra)
        self._f.write(json.dumps(rec) + "\n")

    def step(self, step: int, **extra: Any) -> None:
        rec = {"step": step, "ts": time.time(), "pid": self._pid}
        rec.update(extra)
        self._f.write(json.dumps(rec) + "\n")


def worker_main() -> None:
    t_proc = time.time()
    rg = int(os.environ["REPLICA_GROUP_ID"])
    target = int(os.environ["TPUFT_BENCH_TARGET_STEPS"])
    events_dir = os.environ["TPUFT_BENCH_EVENTS_DIR"]
    mode = os.environ.get("TPUFT_BENCH_MODE", "ddp")
    ev = _EventLog(os.path.join(events_dir, f"replica_{rg}.jsonl"))
    ev.phase("proc_start", ts_override=t_proc)
    stop_path = os.path.join(events_dir, "stop")

    _configure_jax(os.environ.get("TPUFT_BENCH_WORKER_PLATFORM") or None)

    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu import tier as tier_mod
    from torchft_tpu.manager import Manager

    device = jax.devices()[0]  # forces backend init (tunnel dial on TPU)
    ev.phase("jax_ready")

    sizes = {
        f"fleet_{k}": int(os.environ[f"TPUFT_BENCH_{k.upper()}"])
        for k in ("dim", "layers", "seq", "batch", "head_dim")
    }
    model, config = _build_model(sizes, fleet=True)
    # identical init on every replica (the reference seeds identically in its
    # examples; init_sync covers the general case)
    params = jax.device_put(model.init(jax.random.PRNGKey(0)), device)
    inner_tx = optax.adamw(1e-3)
    holder = {"params": params, "opt_state": jax.jit(inner_tx.init)(params)}

    # distinct per-replica data so the replica-dim average does real work
    key = jax.random.PRNGKey(1000 + rg)
    batch_shape = (sizes["fleet_batch"], sizes["fleet_seq"])
    batches = []
    for i in range(4):
        k = jax.random.fold_in(key, i)
        tokens = jax.random.randint(k, batch_shape, 0, config.vocab_size)
        batches.append(
            (jax.device_put(tokens, device), jnp.roll(tokens, -1, axis=1))
        )
    grad_step = jax.jit(jax.value_and_grad(model.loss))
    ev.phase("model_ready")

    gate = os.environ.get("TPUFT_STANDBY_GATE")
    if gate:
        # warm standby (launcher promotes us on the active twin's death):
        # pay the compile + first-execution cost NOW, then park.  A standby
        # must not touch the quorum while parked — the Manager is only
        # constructed after promotion.
        _loss, grads = grad_step(holder["params"], batches[0])
        _sync(grads)
        ev.phase("standby_parked")
        while not os.path.exists(gate) and not os.path.exists(stop_path):
            time.sleep(0.05)
        if os.path.exists(stop_path):
            return
        ev.phase("standby_promoted")

    tier = tier_mod.default_tier()
    comm = tier_mod.make_communicator(timeout_s=30.0)  # data-plane dispatch
    transport = None
    if os.environ.get("TPUFT_BENCH_HEAL_TRANSPORT", "comm") == "comm":
        # heal over the collective fabric (CommTransport) instead of HTTP:
        # same wire the gradients ride, ~an order of magnitude faster per
        # transfer under multi-replica contention (benchmarks/RESULTS.md
        # dcn_bench heal column vs the r5 HTTP heal_recv_s) — HTTP stays
        # selectable for the reference-parity path
        from torchft_tpu.checkpointing.comm_transport import CommTransport

        transport = CommTransport(comm, timeout=60.0)
    manager = Manager(
        comm=comm,
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=1,
        replica_id=f"bench_{rg}",
        use_async_quorum=(mode == "ddp"),
        server_cls=tier_mod.manager_server_cls(tier),
        checkpoint_transport=transport,
    )
    ev.phase("manager_ready", tier=tier)

    if mode == "diloco":
        _worker_diloco(ev, manager, holder, grad_step, inner_tx, batches,
                       target, stop_path)
    else:
        _worker_ddp(ev, manager, holder, grad_step, inner_tx, batches,
                    target, stop_path)
    manager.shutdown()


def _worker_ddp(ev, manager, holder, grad_step, tx, batches, target,
                stop_path) -> None:
    from torchft_tpu.ddp import ft_allreduce
    from torchft_tpu.optim import OptimizerWrapper

    opt = OptimizerWrapper(manager, tx)
    first = True
    first_iter = True
    # the parent ends the phase via the stop file (so a healing victim gets
    # to rejoin even after the survivor passed the measurement target);
    # the hard cap is a runaway backstop
    while not os.path.exists(stop_path) and manager.current_step() < target * 5:
        opt.start_step()
        if first_iter:
            ev.phase("first_started")
        batch = batches[manager.current_step() % len(batches)]
        loss, grads = grad_step(holder["params"], batch)
        if first_iter:
            # sub-attribute the join-to-first-commit window (the round-4
            # breakdown left most of it in one opaque bucket): grads ready
            # (first-step compile + compute), quorum ready (join window +
            # rendezvous/configure + heal transfer, further split by the
            # Manager's own timings), residual = allreduce wire +
            # should_commit barrier + weight update.  One-shot: the heal
            # work happens on the FIRST iteration even when the commit
            # lands on a later one
            _sync(grads)
            ev.phase("first_grads_ready")
            try:
                manager.wait_quorum()
            except Exception:  # noqa: BLE001 — instrumentation must not
                # change failure semantics: Manager.allreduce funnels this
                # same error into a discarded step; a raise here would kill
                # the worker and corrupt the very heal being attributed
                pass
            ev.phase("first_quorum_ready")
            first_iter = False
        grads = ft_allreduce(manager, grads)
        if opt.step(holder, grads):
            if first:
                # quorum timings of the joining round: rpc (incl. barrier +
                # join window), rendezvous/configure, heal transfer
                ev.phase("first_commit", timings=manager.last_quorum_timings)
                first = False
            ev.step(manager.current_step())


def _worker_diloco(ev, manager, holder, grad_step, inner_tx, batches,
                   target, stop_path) -> None:
    import optax

    from torchft_tpu.local_sgd import DiLoCo

    sync_every = int(os.environ.get("TPUFT_BENCH_DILOCO_SYNC", "8"))
    fragments = int(os.environ.get("TPUFT_BENCH_DILOCO_FRAGMENTS", "2"))
    delay = int(os.environ.get("TPUFT_BENCH_DILOCO_DELAY", "2"))
    diloco = DiLoCo(
        manager,
        holder,
        outer_tx=optax.sgd(0.7, momentum=0.9, nesterov=True),
        sync_every=sync_every,
        num_fragments=fragments,
        fragment_sync_delay=delay,
        # quantized pseudogradient sync (int8 default, fp8 via
        # TORCHFT_QUANT_KIND) — the parent resolves the auto-gate and
        # passes a concrete 0/1 in this worker's env
        should_quantize=os.environ.get("TPUFT_BENCH_DILOCO_QUANT_WIRE", "0")
        == "1",
    )
    inner = 0
    first = True
    with diloco:
        while not os.path.exists(stop_path) and inner < target * 5:
            batch = batches[inner % len(batches)]
            loss, grads = grad_step(holder["params"], batch)
            updates, holder["opt_state"] = inner_tx.update(
                grads, holder["opt_state"], holder["params"]
            )
            holder["params"] = optax.apply_updates(holder["params"], updates)
            inner += 1
            committed = diloco.step()
            if committed is not None and first:
                ev.phase("first_commit", timings=manager.last_quorum_timings)
                first = False
            # cyc: position in the sync cycle (the parent times churn kills
            # to land in the fragment-sync window, cyc >= per_frag - delay);
            # outer: committed outer steps
            ev.step(
                inner,
                outer=manager.current_step(),
                cyc=diloco._local_step,
                sync=committed is not None,
            )


# --------------------------------------------------------------------------
# fleet orchestration (phases B, C, D)
# --------------------------------------------------------------------------


def _read_records(events_dir: str, rg: int) -> List[Dict[str, Any]]:
    path = os.path.join(events_dir, f"replica_{rg}.jsonl")
    out: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn final line of a SIGKILLed writer
    except FileNotFoundError:
        pass
    return out


def _steps_of(records: List[Dict[str, Any]]) -> List[Tuple[int, float]]:
    return [
        (r["step"], r["ts"]) for r in records if "step" in r and "ts" in r
    ]


def _phases_of(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    out = []
    for r in records:
        if "phase" in r:
            r = dict(r)
            # proc_start records the pre-import timestamp explicitly
            if "ts_override" in r:
                r["ts"] = r.pop("ts_override")
            out.append(r)
    return out


def run_fleet(
    label: str,
    target_steps: int,
    sizes: Dict[str, int],
    worker_platform: Optional[str],
    kill_every: int = 0,
    replicas: int = 2,
    mode: str = "ddp",
    kill_in_sync_window: bool = False,
    max_kills: Optional[int] = None,
    deadline_s: Optional[float] = None,
    extra_env: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Run a fleet of replica-group subprocesses to ``target_steps`` on the
    anchor (replica 0, never killed); if ``kill_every`` > 0, SIGKILL a
    rotating victim among replicas 1..N-1 every ``kill_every`` anchor steps
    (waiting for the previous victim to rejoin first, so each heal-in is
    well defined).  Returns throughput + heal stats from the per-replica
    event logs."""
    from torchft_tpu import tier as tier_mod
    from torchft_tpu.launcher import ReplicaSpec, ReplicaSupervisor

    events_dir = tempfile.mkdtemp(prefix=f"tpuft_bench_{label}_")
    tier = tier_mod.default_tier()
    lighthouse = tier_mod.make_lighthouse(
        bind="127.0.0.1:0",
        min_replicas=1,
        # the join window is pure heal-in latency for a rejoining victim
        # (its quorum RPC parks for the full window when membership grows);
        # 1 s is ample straggler slack for localhost RPC while keeping the
        # standby-promotion heal in the join+transfer regime
        join_timeout_ms=int(os.environ.get("TPUFT_BENCH_JOIN_MS", "1000")),
        quorum_tick_ms=50,
        tier=tier,
    )
    env = {
        "TPUFT_BENCH_EVENTS_DIR": events_dir,
        "TPUFT_BENCH_TARGET_STEPS": str(target_steps),
        "TPUFT_BENCH_WORKER_PLATFORM": worker_platform or "",
        "TPUFT_BENCH_MODE": mode,
        "TPUFT_BENCH_DILOCO_SYNC": str(sizes["diloco_sync_every"]),
        "TPUFT_BENCH_DILOCO_FRAGMENTS": str(sizes["diloco_fragments"]),
        "TPUFT_BENCH_DILOCO_DELAY": str(sizes["diloco_sync_delay"]),
    }
    for k in ("dim", "layers", "seq", "batch", "head_dim"):
        env[f"TPUFT_BENCH_{k.upper()}"] = str(sizes[f"fleet_{k}"])
    if extra_env:
        env.update(extra_env)
    standby = bool(sizes.get("standby")) and kill_every > 0
    # with >= 3 replicas, leave the LAST victim cold (no spare): victim
    # rotation then produces both heal paths in one artifact, so the
    # standby-vs-cold heal-in comparison is measured, not assumed
    all_standby = os.environ.get("TPUFT_BENCH_ALL_STANDBY", "") not in ("", "0")
    cold_victim = (
        replicas - 1 if standby and replicas > 2 and not all_standby else None
    )
    specs = [
        ReplicaSpec(
            replica_group_id=i,
            cmd=[sys.executable, os.path.abspath(__file__), "--worker"],
            env=dict(env),
            # spares only behind killable replicas (0 is the anchor)
            standby=standby and i != 0 and i != cold_victim,
        )
        for i in range(replicas)
    ]
    supervisor = ReplicaSupervisor(
        specs,
        f"127.0.0.1:{lighthouse.port}",
        restart_delay_s=0.5,
    )
    runner = threading.Thread(target=supervisor.run, daemon=True)
    runner.start()

    # fragment-sync window start, in inner-cycle position (phase D kills
    # must land while the pseudogradient allreduce is in flight)
    per_frag = sizes["diloco_sync_every"] // sizes["diloco_fragments"]
    sync_cyc = per_frag - sizes["diloco_sync_delay"]

    kills: List[Dict[str, Any]] = []
    next_kill = kill_every
    victim = 1 if replicas > 1 else 0
    if deadline_s is None:
        deadline_s = 240.0 + 3.0 * target_steps + 90.0 * (
            (target_steps // kill_every) if kill_every else 0
        )
    deadline = time.time() + deadline_s
    heal_grace_s = 120.0
    stop_path = os.path.join(events_dir, "stop")
    try:
        while time.time() < deadline:
            anchor = _steps_of(_read_records(events_dir, 0))
            # gate on the PREVIOUS kill's victim having rejoined (committed
            # a step since its kill) — with rotation the next victim is a
            # different, healthy replica, and killing it while the last one
            # is still healing would overlap heals and corrupt attribution
            victim_back = bool(_steps_of(_read_records(events_dir, victim)))
            if kills:
                prev = _steps_of(_read_records(events_dir, kills[-1]["victim"]))
                victim_back = (
                    victim_back and bool(prev) and prev[-1][1] > kills[-1]["ts"]
                )
            if anchor and anchor[-1][0] >= target_steps:
                # anchor hit the measurement target; linger (bounded) so a
                # mid-heal victim gets to rejoin — that rejoin is the
                # heal-in data point
                if (
                    not kills
                    or victim_back
                    or time.time() - kills[-1]["ts"] > heal_grace_s
                ):
                    break
            elif (
                kill_every
                and anchor
                and anchor[-1][0] >= next_kill
                and victim_back
                and (max_kills is None or len(kills) < max_kills)
            ):
                if kill_in_sync_window:
                    # only pull the trigger while the victim reports being
                    # inside the fragment-sync window
                    cyc = next(
                        (
                            r.get("cyc")
                            for r in reversed(_read_records(events_dir, victim))
                            if "step" in r
                        ),
                        None,
                    )
                    if cyc is None or cyc < sync_cyc:
                        time.sleep(0.1)
                        continue
                if supervisor.kill(victim):
                    kills.append(
                        {
                            "ts": time.time(),
                            "survivor_step": anchor[-1][0],
                            "victim": victim,
                        }
                    )
                    print(
                        f"bench[{label}]: killed replica {victim} at anchor "
                        f"step {anchor[-1][0]}",
                        file=sys.stderr,
                    )
                    next_kill = anchor[-1][0] + kill_every
                    # rotate the victim among 1..N-1
                    if replicas > 2:
                        victim = 1 + (victim % (replicas - 1))
            time.sleep(0.25)
    finally:
        with open(stop_path, "w") as f:
            f.write("stop")
        runner.join(timeout=60)
        supervisor.stop()
        lighthouse.shutdown()

    records = [_read_records(events_dir, i) for i in range(replicas)]
    return _fleet_metrics(label, target_steps, records, kills)


def _fleet_metrics(
    label: str,
    target_steps: int,
    records: List[List[Dict[str, Any]]],
    kills: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Throughput + heal statistics from the committed-step event logs.

    All replica processes share one physical chip in this harness, so
    survivors literally speed up while a peer is dead (decontention) — a
    raw with-faults/fault-free wall-clock ratio would overstate fault
    tolerance.  Instead the fault cost is measured directly: the anchor's
    steady-state step time during all-alive periods (``t_step_s``) vs the
    extra time its disrupted steps took around each kill and each rejoin
    (``overhead_per_kill_s``).  BASELINE's fault rate is one kill per 100
    steps, so the north-star ratio is ``100·t / (100·t + overhead)``.
    """
    evs = [_steps_of(r) for r in records]
    anchor = evs[0]
    result: Dict[str, Any] = {
        "label": label,
        "replicas": len(records),
        "kills": len(kills),
        "anchor_steps": anchor[-1][0] if anchor else 0,
        "completed": bool(anchor and anchor[-1][0] >= target_steps),
    }
    if len(anchor) < 2:
        return result

    # per-step durations for the anchor: dts[i] = time to commit anchor[i]
    dts = [
        (anchor[i][0], anchor[i][1], anchor[i][1] - anchor[i - 1][1])
        for i in range(1, len(anchor))
    ]

    def _outstanding(ts: float) -> bool:
        """True when some kill before ``ts`` has no victim rejoin yet."""
        for kill in kills:
            if kill["ts"] > ts:
                continue
            vic = evs[kill["victim"]]
            if not any(kill["ts"] < t <= ts for (_s, t) in vic):
                return True
        return False

    steady = [dt for (_s, ts, dt) in dts if not _outstanding(ts)]
    if steady:
        steady_sorted = sorted(steady)
        t_step = steady_sorted[len(steady_sorted) // 2]
        result["t_step_s"] = round(t_step, 4)
        result["anchor_steps_per_sec"] = round(1.0 / t_step, 3)
    else:
        t_step = None

    # wall-clock throughput over the whole phase (raw, contention-skewed)
    span_steps = anchor[-1][0] - anchor[0][0]
    span_time = anchor[-1][1] - anchor[0][1]
    if span_steps > 0 and span_time > 0:
        result["anchor_steps_per_sec_raw"] = round(span_steps / span_time, 3)

    # DiLoCo: cost of a fragment sync = median sync-step time minus median
    # plain-inner-step time (how well the τ-delayed allreduce overlaps)
    anchor_steps_recs = [r for r in records[0] if "step" in r]
    if t_step is not None and any(r.get("sync") for r in anchor_steps_recs):
        by_ts = {r["ts"]: bool(r.get("sync")) for r in anchor_steps_recs}
        sync_dts = sorted(
            dt for (_s, ts, dt) in dts
            if by_ts.get(ts) and not _outstanding(ts)
        )
        plain_dts = sorted(
            dt for (_s, ts, dt) in dts
            if not by_ts.get(ts) and not _outstanding(ts)
        )
        if sync_dts and plain_dts:
            sync_t = sync_dts[len(sync_dts) // 2]
            plain_t = plain_dts[len(plain_dts) // 2]
            result["sync_step_s"] = round(sync_t, 4)
            result["inner_step_s"] = round(plain_t, 4)
            result["sync_overhead_s"] = round(max(0.0, sync_t - plain_t), 4)

    # per-kill disruption + heal attribution
    heal_secs: List[float] = []
    heal_steps: List[int] = []
    overheads: List[float] = []
    breakdowns: List[Dict[str, float]] = []
    by_victim: Dict[int, List[float]] = {}
    for kill in kills:
        # the rejoin record (first committed step after the kill) — read it
        # once so ts and the rejoining incarnation's pid come from the same
        # event (matching them up later by float ts equality would be
        # fragile)
        rejoin_rec = next(
            (
                r
                for r in records[kill["victim"]]
                if "step" in r and r["ts"] > kill["ts"]
            ),
            None,
        )
        rejoin_ts = rejoin_rec["ts"] if rejoin_rec else None
        if rejoin_ts is not None:
            heal_secs.append(rejoin_ts - kill["ts"])
            by_victim.setdefault(kill["victim"], []).append(
                rejoin_ts - kill["ts"]
            )
            anchor_at_rejoin = max(
                (s for (s, t) in anchor if t <= rejoin_ts),
                default=kill["survivor_step"],
            )
            heal_steps.append(
                max(0, anchor_at_rejoin - kill["survivor_step"])
            )
            bd = _heal_breakdown(
                records[kill["victim"]],
                kill["ts"],
                rejoin_ts,
                rejoin_rec.get("pid"),
            )
            if bd:
                breakdowns.append(bd)
        if t_step is not None:
            if rejoin_ts is not None:
                window_end = rejoin_ts + 3 * t_step
            else:
                window_end = kill["ts"] + 10 * t_step
            dis = [
                dt for (_s, ts, dt) in dts if kill["ts"] <= ts <= window_end
            ]
            overheads.append(sum(max(0.0, dt - t_step) for dt in dis))
    if heal_secs:
        # seconds is the environment-independent number (process respawn +
        # jax init + rejoin + heal transfer); steps would scale with the
        # survivor's decontended step time and mislead
        result["mean_heal_in_s"] = round(sum(heal_secs) / len(heal_secs), 1)
        result["heal_in_s"] = [round(h, 1) for h in heal_secs]
        result["heal_in_steps"] = heal_steps
        result["heal_by_victim"] = {
            str(v): [round(h, 1) for h in hs] for v, hs in by_victim.items()
        }
    if breakdowns:
        numeric_keys = sorted(
            {
                k
                for bd in breakdowns
                for k, v in bd.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
        )
        agg: Dict[str, Any] = {
            # mean over the kills in which the phase occurred (a key absent
            # from a breakdown means that heal path skipped the phase, not
            # that it took 0 s — cold respawns have no promote_s and
            # standby promotions have no respawn_s)
            k: round(
                sum(bd[k] for bd in breakdowns if k in bd)
                / sum(1 for bd in breakdowns if k in bd),
                2,
            )
            for k in numeric_keys
        }
        agg["paths"] = {
            p: sum(1 for bd in breakdowns if bd.get("path") == p)
            for p in {bd.get("path") for bd in breakdowns}
        }
        agg["all_sane"] = all(bd.get("sane") for bd in breakdowns)
        result["heal_breakdown"] = agg
        result["heal_breakdowns"] = breakdowns
        # mean heal-in per path: the warm-standby payoff (vs cold respawn)
        # measured head-to-head in one artifact.  breakdowns[i] and
        # heal_secs[i] describe the same rejoin (appended together above)
        if len(breakdowns) == len(heal_secs):
            by_path: Dict[str, List[float]] = {}
            for bd, h in zip(breakdowns, heal_secs):
                by_path.setdefault(bd["path"], []).append(h)
            result["heal_in_s_by_path"] = {
                p: round(sum(hs) / len(hs), 1) for p, hs in by_path.items()
            }
    if overheads:
        result["overhead_per_kill_s"] = round(
            sum(overheads) / len(overheads), 3
        )
        if t_step:
            per100 = 100.0 * t_step
            result["ratio_per_100step_kill"] = round(
                per100 / (per100 + result["overhead_per_kill_s"]), 4
            )
    return result


def _heal_breakdown(
    victim_records: List[Dict[str, Any]],
    kill_ts: float,
    rejoin_ts: float,
    rejoin_pid: Optional[int],
) -> Dict[str, Any]:
    """Attribute one victim rejoin to phases, from its phase log:
    respawn (supervisor delay + python boot), jax_init (backend/tunnel
    dial), model_build (init + device_put + trace), promote (death
    detection + gate release, warm-standby path), manager (ctor + server
    + store), join_to_first_commit (quorum rpc incl. join window,
    rendezvous, checkpoint transfer — sub-attributed from Manager timings,
    plus first-step compile).

    Only the **rejoining incarnation's** phases count (matched by pid): the
    group's log interleaves the killed process, the promoted standby, and
    the fresh spare re-warmed behind it — the spare's boot phases land
    inside the kill→rejoin window but are off the heal path (round-3
    artifact had ``promote_s = -5.44`` from exactly this mixing)."""
    phases = [
        p
        for p in _phases_of(victim_records)
        if kill_ts < p["ts"] <= rejoin_ts
        and (rejoin_pid is None or p.get("pid") == rejoin_pid)
    ]
    t = {p["phase"]: p for p in phases}
    out: Dict[str, Any] = {}
    prev = kill_ts
    for name, key in (
        ("proc_start", "respawn_s"),
        ("jax_ready", "jax_init_s"),
        ("model_ready", "model_build_s"),
        # warm-standby takeover: the phases above are absent — the spare
        # paid them before the kill, while parked
        ("standby_promoted", "promote_s"),
        ("manager_ready", "manager_s"),
        # sub-attribution of the join window (ddp workers log these ONCE,
        # on their first loop iteration): loop entry, first grads computed
        # (compile + compute), quorum ready (join + configure + heal
        # transfer).  Best-effort: when the first quorum funnels an error
        # and the real heal happens on iteration 2+, the later work lands
        # in the residual below — visible as a large join_to_first_commit_s
        # rather than misattributed to a named phase
        ("first_started", "first_loop_s"),
        ("first_grads_ready", "first_grads_s"),
        ("first_quorum_ready", "quorum_wait_s"),
    ):
        if name in t:
            out[key] = t[name]["ts"] - prev
            prev = t[name]["ts"]
    # residual after the last logged phase: allreduce wire + should_commit
    # barrier + weight update (small once the sub-phases above exist)
    out["join_to_first_commit_s"] = rejoin_ts - prev
    # trust signal: every phase must be non-negative (the walk chains
    # timestamps of ONE process, so a negative means cross-incarnation
    # mixing), and the rejoiner must have logged manager_ready — it cannot
    # have committed a step without constructing a Manager, so its absence
    # means the pid filter matched the wrong (or no) incarnation
    numeric = [v for v in out.values() if isinstance(v, float)]
    out["path"] = "standby" if "standby_promoted" in t else "cold"
    out["sane"] = bool(
        all(v >= -1e-6 for v in numeric) and "manager_ready" in t
    )
    fc = t.get("first_commit")
    if fc and isinstance(fc.get("timings"), dict):
        for k, v in fc["timings"].items():
            out[f"quorum_{k}"] = v
    return {
        k: (round(v, 3) if isinstance(v, float) else v)
        for k, v in out.items()
    }


# --------------------------------------------------------------------------
# phase A: single-chip ws=1 overhead + absolute tokens/sec/chip + MFU
# --------------------------------------------------------------------------


def run_single(sizes: Dict[str, int]) -> Dict[str, Any]:
    """Phase A with remat-mode walk: cheaper-recompute modes are tried
    first and an activation OOM falls back toward full per-layer remat."""
    modes = _phase_a_modes(sizes)
    last_err: Optional[BaseException] = None
    for i, mode in enumerate(modes):
        try:
            return _run_single_mode(sizes, mode)
        except Exception as e:  # noqa: BLE001 — inspect for OOM class
            msg = str(e)
            oom = (
                "RESOURCE_EXHAUSTED" in msg
                or "Out of memory" in msg
                or "out of memory" in msg
                or isinstance(e, MemoryError)
            )
            if oom and i + 1 < len(modes):
                print(
                    f"bench: phase A remat mode {mode!r} OOM; retrying "
                    f"with {modes[i + 1]!r}",
                    file=sys.stderr,
                )
                # drop the traceback: it pins the failed attempt's frame —
                # and with it the params/opt buffers in HBM — which would
                # make the fallback mode OOM too
                last_err = e.with_traceback(None)
                continue
            raise
    raise last_err  # pragma: no cover - loop always returns or raises


def _run_single_mode(sizes: Dict[str, int], remat_mode: str) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import optax

    from torchft_tpu import tier as tier_mod
    from torchft_tpu.ddp import ft_allreduce
    from torchft_tpu.manager import Manager
    from torchft_tpu.optim import OptimizerWrapper

    steps = sizes["steps"]
    model, config = _build_model(sizes, remat_mode=remat_mode)
    device = jax.devices()[0]
    flash = model._use_flash(sizes["seq"])
    print(
        f"bench: llama dim={config.dim} layers={config.n_layers} "
        f"seq={sizes['seq']} batch={sizes['batch']} "
        f"heads={config.n_heads}x{config.head_dim} "
        f"params={model.num_params()/1e6:.1f}M remat={remat_mode} "
        f"flash={flash} on {device.platform} ({device.device_kind})",
        file=sys.stderr,
    )

    params = jax.device_put(model.init(jax.random.PRNGKey(0)), device)
    tx = optax.adamw(1e-3)
    key = jax.random.PRNGKey(1)
    tokens = jax.random.randint(
        key, (sizes["batch"], sizes["seq"]), 0, config.vocab_size
    )
    targets = jnp.roll(tokens, -1, axis=1)
    batch_data = (jax.device_put(tokens, device), jax.device_put(targets, device))
    tokens_per_step = sizes["batch"] * sizes["seq"]

    grad_step = jax.jit(jax.value_and_grad(model.loss))

    # fault-free baseline: ALL measured steps inside ONE jitted lax.scan.
    # Under the axon tunnel every python-level dispatch pays a network RTT
    # and ``block_until_ready`` doesn't truly block, so a python step loop
    # both under-measures (dispatch gaps) and mis-measures; the scan chain
    # is the honest peak-compute number (one dispatch, data-dependent
    # carry so XLA can't elide work, one D2H sync at the end) and is what
    # MFU is computed from.  The FT path below stays a per-step python
    # loop — its protocol work is host-side by design — so ``ws1_ratio``
    # now includes per-step dispatch overhead, reported separately.
    def train_scan(p, o):
        def body(carry, _):
            p, o = carry
            loss, grads = jax.value_and_grad(model.loss)(p, batch_data)
            updates, o = tx.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o), loss

        (p, o), losses = jax.lax.scan(body, (p, o), None, length=steps)
        return p, o, losses

    # deep copy: the scan donates its inputs, and the FT phase below must
    # not read donated buffers
    ff_params = jax.tree_util.tree_map(jnp.copy, params)
    opt_state = jax.jit(tx.init)(ff_params)
    scan_compiled = (
        jax.jit(train_scan, donate_argnums=(0, 1))
        .lower(ff_params, opt_state)
        .compile()
    )
    # one short warmup dispatch settles the tunnel before timing
    loss0, grads0 = grad_step(params, batch_data)
    _sync(loss0)
    del loss0, grads0

    start = time.perf_counter()
    ff_params, opt_state, losses = scan_compiled(ff_params, opt_state)
    _sync(losses)
    faultfree_s = (time.perf_counter() - start) / steps
    faultfree_tps = tokens_per_step / faultfree_s
    print(
        f"fault-free (scan x{steps}): {faultfree_s*1e3:.1f} ms/step, "
        f"{faultfree_tps:,.0f} tok/s",
        file=sys.stderr,
    )
    # free the baseline's params+optimizer copies BEFORE the FT stack
    # allocates its own — at ~1B params two live copies OOM a single chip
    del ff_params, opt_state, losses, scan_compiled
    _sync(params)

    # full FT stack, ws=1, on the production tier
    tier = tier_mod.default_tier()
    lighthouse = tier_mod.make_lighthouse(
        bind="127.0.0.1:0",
        min_replicas=1,
        join_timeout_ms=50,
        quorum_tick_ms=20,
        tier=tier,
    )
    holder = {"params": params, "opt_state": jax.jit(tx.init)(params)}
    manager = None
    try:
        manager = Manager(
            comm=tier_mod.make_communicator(timeout_s=60.0),
            load_state_dict=lambda s: holder.update(s),
            state_dict=lambda: dict(holder),
            min_replica_size=1,
            replica_id="bench_0",
            lighthouse_addr=lighthouse.local_address(),
            server_cls=tier_mod.manager_server_cls(tier),
        )
        opt = OptimizerWrapper(manager, tx)

        def ft_step() -> None:
            opt.start_step()
            loss, grads = grad_step(holder["params"], batch_data)
            grads = ft_allreduce(manager, grads)
            opt.step(holder, grads)

        for _ in range(4):  # warm the protocol path + post-compile iterations
            ft_step()
        _sync(holder["params"])

        start = time.perf_counter()
        for _ in range(steps):
            ft_step()
        _sync(holder["params"])
        ft_s = (time.perf_counter() - start) / steps
        ft_tps = tokens_per_step / ft_s
        print(
            f"ft: {ft_s*1e3:.1f} ms/step, {ft_tps:,.0f} tok/s", file=sys.stderr
        )
    finally:
        # shutdown on EVERY path: an OOM here sends run_single to the next
        # remat mode, and a leaked Manager's state_dict closure would pin
        # holder's params + opt_state in HBM (and stack live servers per
        # retry)
        if manager is not None:
            manager.shutdown()
        lighthouse.shutdown()

    # achieved model FLOPs: the standard 6N per token for the train step
    # (fwd+bwd) plus the attention score/value matmuls 12·L·dim·S.  N
    # excludes the embedding table (a gather, not a matmul — PaLM MFU
    # convention) but keeps the lm_head projection, which is a real matmul
    matmul_params = model.num_params() - config.vocab_size * config.dim
    flops_per_token = (
        6 * matmul_params + 12 * config.n_layers * config.dim * sizes["seq"]
    )
    # MFU from the scanned fault-free loop (the compute stack's ceiling —
    # one dispatch, no host protocol); the FT path's throughput and its
    # own MFU are reported alongside so the protocol + dispatch tax is
    # visible rather than folded into the headline
    tflops = faultfree_tps * flops_per_token / 1e12
    ft_tflops = ft_tps * flops_per_token / 1e12
    out = {
        "faultfree_tokens_per_sec": round(faultfree_tps, 1),
        "ft_tokens_per_sec": round(ft_tps, 1),
        "ws1_ratio": round(ft_tps / faultfree_tps, 4),
        "model_tflops_per_sec": round(tflops, 2),
        "ft_model_tflops_per_sec": round(ft_tflops, 2),
        "platform": device.platform,
        "device_kind": device.device_kind,
        "tier": tier,
        "remat": remat_mode,
        "flash": bool(flash),
    }
    peak = _peak_tflops(device)
    if peak:
        out["peak_tflops"] = peak
        out["mfu"] = round(tflops / peak, 4)
        out["mfu_ft"] = round(ft_tflops / peak, 4)
        factor = _REMAT_HW_FACTOR.get(remat_mode, 1.0)
        if factor > 1.0:
            # remat re-runs part of the forward in the backward: hardware
            # does ~factor*6N/token against the 6N the MFU convention counts
            out["hw_mfu_est"] = round(tflops * factor / peak, 4)
    print(
        f"bench: {tflops:.2f} model TFLOP/s (scan), {ft_tflops:.2f} (ft), "
        f"mfu={out.get('mfu')} mfu_ft={out.get('mfu_ft')}",
        file=sys.stderr,
    )
    return out


def _headline_heal_keys(faults: Dict[str, Any]) -> Dict[str, Any]:
    """Lift the aggregated ``heal_breakdown`` phases into top-level
    headline keys (respawn / join / transfer / first-commit, plus the
    standby promote phase) so the spare-promotion gate is comparable
    round-over-round without digging into bench_out.json.  A key is None
    when no kill exercised that phase this round (cold respawns have no
    promote_s, standby promotions no respawn_s)."""
    bd = faults.get("heal_breakdown") or {}
    return {
        "heal_respawn_s": bd.get("respawn_s"),
        "heal_join_s": bd.get("quorum_wait_s"),
        "heal_transfer_s": bd.get("quorum_heal_recv_s"),
        "heal_first_commit_s": bd.get("join_to_first_commit_s"),
        "heal_promote_s": bd.get("promote_s"),
    }


def _run_spare_phase(num_replicas: int = 3, steps: int = 10) -> Dict[str, Any]:
    """Hot-spare promotion gate: the thread-plane spare drill (3 actives +
    1 continuously-warmed spare, one active killed) under the ``wan_1g``
    profile.  Reports ``mean_heal_in_s`` via promotion, to sit side by
    side with the process fleet's cold/standby heal-in — the PR-6 payoff
    (<1 s vs 6–12 s) measured in one artifact."""
    from torchft_tpu.drill import gray_failure_drill

    saved = {k: os.environ.get(k) for k in ("TORCHFT_NET_EMU",)}
    os.environ["TORCHFT_NET_EMU"] = "wan_1g"
    try:
        report = gray_failure_drill(
            mode="spare_promote", num_replicas=num_replicas, steps=steps
        )
        return {
            "profile": "wan_1g",
            "replicas": num_replicas,
            "spares": 1,
            "mean_heal_in_s": report["mean_heal_in_s"],
            "promotion_latency_s": report["promotion_latency_s"],
            "warm_lag_steps": report["warm_lag_steps"],
            "quorum_reconfigs": report["quorum_reconfigs"],
            "promotions_total": report["promotions_total"],
        }
    except Exception as e:  # noqa: BLE001 — a failed drill is a recorded
        # fact, never a lost artifact
        return {"error": f"{type(e).__name__}: {e}"}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _run_degraded_phase(num_replicas: int = 3, steps: int = 10) -> Dict[str, Any]:
    """Degraded-mode gate (ISSUE 13): two thread-plane drills under the
    ``wan_1g`` profile — (a) an in-replica device loss absorbed in place
    (``degraded_step_time_ratio``: wounded-fleet step time vs the pre-wound
    baseline, zero membership edits), and (b) the same wound with a warm
    full-width spare registered (``wound_to_swap_s``: wound detection →
    spare swapped in as ONE membership edit)."""
    from torchft_tpu.drill import gray_failure_drill

    saved = {k: os.environ.get(k) for k in ("TORCHFT_NET_EMU",)}
    os.environ["TORCHFT_NET_EMU"] = "wan_1g"
    out: Dict[str, Any] = {"profile": "wan_1g", "replicas": num_replicas}
    try:
        try:
            wound = gray_failure_drill(
                mode="device_loss", num_replicas=num_replicas, steps=steps
            )
            out.update(
                degraded_step_time_ratio=wound.get("degraded_step_time_ratio"),
                capacity_observed=wound.get("capacity_observed"),
                wound_quorum_reconfigs=wound.get("quorum_reconfigs"),
                converged=wound.get("converged"),
            )
        except Exception as e:  # noqa: BLE001 — a failed drill is a
            # recorded fact, never a lost artifact
            out["device_loss_error"] = f"{type(e).__name__}: {e}"
        try:
            swap = gray_failure_drill(
                mode="device_loss_swap",
                num_replicas=num_replicas,
                steps=steps,
            )
            out.update(
                wound_to_swap_s=swap.get("wound_to_swap_s"),
                swaps_total=swap.get("swaps_total"),
                swap_quorum_reconfigs=swap.get("quorum_reconfigs"),
            )
        except Exception as e:  # noqa: BLE001
            out["swap_error"] = f"{type(e).__name__}: {e}"
        return out
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _run_coord_phase(num_replicas: int) -> Dict[str, Any]:
    """Coordination-plane scale gate (ISSUE 12): the thread-plane harness
    drives ``num_replicas`` simulated replicas + a spare pool through
    quorum/kill/rejoin/promote churn and an aggregator bounce against a
    subprocess lighthouse, reporting p99 quorum latency, lighthouse CPU,
    and the lighthouse-inbound beat-RPC reduction vs direct heartbeats.
    Pure control plane — no accelerator, no data plane — so it costs tens
    of seconds regardless of platform."""
    from torchft_tpu.coord.scale import run_scale_harness

    try:
        return run_scale_harness(
            num_replicas=num_replicas,
            num_aggregators=2,
            num_spares=2,
            kills=1,
            rejoins=1,
            agg_bounce=True,
            deadline_s=150.0,
        )
    except Exception as e:  # noqa: BLE001 — a failed phase is a recorded
        # fact, never a lost artifact
        return {"error": f"{type(e).__name__}: {e}"}


def _run_obs_phase() -> Dict[str, Any]:
    """Observability-overhead gate (ISSUE 14): the flight recorder + trace
    spans must cost <= 1% of step time when fully enabled.

    Two measurements, combined as a ratio:

    - **step time**: a synthetic step (a fixed numpy matmul workload sized
      to a few milliseconds — conservative: a real train step is orders of
      magnitude longer, making the same absolute obs cost proportionally
      smaller), median over ``TPUFT_BENCH_OBS_STEPS``.
    - **obs cost per step**: the per-step event/span pattern the real
      protocol emits (~14 events + ~8 spans: quorum start/adopt, lane
      windows, vote/commit) run WITHOUT the workload, thousands of
      repetitions, enabled minus disabled — the marginal cost of turning
      recorder + spans on, measured to sub-microsecond resolution instead
      of differencing two multi-millisecond legs whose ambient jitter
      would swamp a <1% effect.

    ``obs_overhead_frac = obs_cost_per_step / step_time``."""
    import numpy as np

    from torchft_tpu.obs import spans as obs_spans
    from torchft_tpu.obs.flight import FlightEvent, FlightRecorder

    steps = int(os.environ.get("TPUFT_BENCH_OBS_STEPS", "") or 40)
    rng = np.random.default_rng(3)
    a = rng.normal(size=(896, 896)).astype(np.float32)
    b = rng.normal(size=(896, 896)).astype(np.float32)

    def obs_pattern(rec: FlightRecorder, i: int) -> None:
        span = obs_spans.span
        rec.set_context(step=i, quorum_id=1)
        rec.record(FlightEvent.QUORUM_START, step=i)
        with span("manager::quorum_rpc", step=i):
            rec.record(FlightEvent.QUORUM_ADOPT, step=i, world=3)
        with span("comm::op", epoch=1):
            for lane in range(4):
                with span("comm::lane_window", lane=lane):
                    rec.record(
                        FlightEvent.COMM_CONFIGURE, rank=0, world=3, lanes=4
                    )
        with span("manager::fence", step=i):
            rec.record(FlightEvent.COMMIT_FENCE, step=i)
        for _ in range(6):  # heal/lane/chaos-shaped background events
            rec.record(FlightEvent.LANE_RECONNECT, peer=1, lane=0)
        rec.record(FlightEvent.COMMIT_VOTE, step=i, local=True)
        with span("manager::should_commit", step=i):
            rec.record(FlightEvent.COMMIT_RESULT, step=i, committed=True)

    def measure_pattern(rec: FlightRecorder, spans_on: bool, reps: int) -> float:
        obs_spans.configure(spans_on)
        for i in range(50):  # warm caches + the allocator
            obs_pattern(rec, i)
        t0 = time.perf_counter()
        for i in range(reps):
            obs_pattern(rec, i)
        return (time.perf_counter() - t0) / reps

    saved_enabled = obs_spans._enabled
    try:
        # the step the tax is measured against (median beats jitter)
        times = []
        for _ in range(max(8, steps)):
            t0 = time.perf_counter()
            _ = a @ b
            _ = a @ b
            times.append(time.perf_counter() - t0)
        t_step = float(np.median(times))

        off_rec = FlightRecorder("bench_obs_off", cap=0)
        on_rec = FlightRecorder("bench_obs_on", cap=4096)
        reps = 2000
        t_pat_off = measure_pattern(off_rec, spans_on=False, reps=reps)
        t_pat_on = measure_pattern(on_rec, spans_on=True, reps=reps)
        obs_cost = max(0.0, t_pat_on - t_pat_off)
        frac = obs_cost / t_step if t_step > 0 else 0.0
        return {
            "obs_overhead_frac": round(frac, 5),
            "step_ms": round(t_step * 1e3, 4),
            "obs_cost_us_per_step": round(obs_cost * 1e6, 3),
            "pattern_us_disabled": round(t_pat_off * 1e6, 3),
            "pattern_us_enabled": round(t_pat_on * 1e6, 3),
            "events_per_step": 14,
            "spans_per_step": 8,
            "events_recorded": len(on_rec),
            "spans_recorded": len(obs_spans.snapshot()),
        }
    finally:
        obs_spans.configure(saved_enabled)
        obs_spans.clear()


_PARTIAL: Dict[str, Any] = {}
# overridable so a recovery subprocess (see _try_tpu_phase_a) never
# clobbers the parent run's streaming artifact
_PARTIAL_PATH = os.environ.get(
    "TPUFT_BENCH_OUT", os.path.join(REPO, "bench_out.json")
)


def _emit_partial(**updates: Any) -> None:
    """Stream results to ``bench_out.json`` as each phase completes, so a
    driver that captures only the output tail — or a late-phase hang — can
    never lose the already-measured numbers (round 3 lost the MFU head to
    exactly that truncation)."""
    _PARTIAL.update(updates)
    _PARTIAL["partial_ts"] = round(time.time(), 1)
    try:
        tmp = _PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_PARTIAL, f, indent=1)
        os.replace(tmp, _PARTIAL_PATH)
    except OSError as e:  # a broken sink must not kill the bench
        print(f"bench: cannot write {_PARTIAL_PATH}: {e}", file=sys.stderr)


def _install_hard_deadline(deadline_ts: float):
    """Last-resort watchdog for the driver's external ``timeout`` wrapper.

    The soft budget checks run BETWEEN phases, so one overrunning phase
    (round 5: the DiLoCo sweep on a slow CPU) can sail past the budget and
    let the external ``timeout`` SIGKILL the bench — rc=124, no final JSON
    line, the whole round lost (BENCH_r05 recorded exactly that: ``parsed:
    null`` with every per-scenario number already measured).  At
    ``deadline_ts`` this thread flushes the partial per-scenario artifact,
    prints a complete headline JSON line assembled from whatever phases
    finished, and exits 0 — a truncated-but-parseable round beats a dead
    one.  ``os._exit`` on purpose: the wedged phase may be blocked in
    uninterruptible jax/socket calls that a SystemExit would never unwind.

    Returns the armed ``threading.Timer`` so the caller can cancel and
    RE-ARM it tighter once the probe window resolves: the round-5 escape
    was exactly this gap — the install-time deadline must cover a wedged
    900 s probe, but on runs where the probe returns in seconds that slack
    let the in-process legs (the XLA-warmup single phase and the DiLoCo
    sub-legs, which enforce budgets only BETWEEN fleets) outlive the
    driver's external timeout before the watchdog ever fired.
    """
    import threading

    def _fire() -> None:
        _emit_partial(deadline_expired=True)
        single = _PARTIAL.get("single") or {}
        headline = {
            "metric": "ft_vs_faultfree_tokens_per_sec_ratio",
            "value": single.get("ws1_ratio"),
            "unit": "ratio",
            "platform": single.get("platform"),
            "tier": single.get("tier"),
            "mfu": single.get("mfu"),
            # coordination headline keys land even on a watchdog trip —
            # they streamed into _PARTIAL the moment the phase finished
            "coord_p99_quorum_latency_s": _PARTIAL.get(
                "coord_p99_quorum_latency_s"
            ),
            "lighthouse_cpu_frac": _PARTIAL.get("lighthouse_cpu_frac"),
            "deadline_expired": True,
            "phases_done": sorted(
                k for k in _PARTIAL if k not in ("partial_ts", "final")
            ),
            "detail": "bench_out.json",
        }
        print(
            "bench: HARD DEADLINE expired — emitting partial artifact and "
            "exiting 0 (see bench_out.json for completed phases)",
            file=sys.stderr,
        )
        print(json.dumps(headline), flush=True)
        sys.stderr.flush()
        os._exit(0)

    delay = deadline_ts - time.time()
    if delay <= 0:
        _fire()
    timer = threading.Timer(delay, _fire)
    timer.daemon = True
    timer.start()
    return timer


def capture_phase_a_subprocess(
    budget_s: float,
    out_path: Optional[str] = None,
    probe_window_s: float = 120.0,
    log=lambda m: print(f"bench: {m}", file=sys.stderr),
) -> Optional[Dict[str, Any]]:
    """Run a phase-A-only bench (fleet/DiLoCo skipped) on the DEFAULT jax
    backend in a fresh subprocess and return its full streaming artifact —
    or None when it failed or fell back to CPU.  The single capture
    protocol shared by the mid-run recovery below and
    ``scripts/tpu_watch.py`` (one place to change env knobs / artifact
    keys)."""
    import subprocess

    if out_path is None:
        out_path = os.path.join(
            tempfile.mkdtemp(prefix="tpuft_bench_capture_"), "phase_a.json"
        )
    elif os.path.exists(out_path):
        # a reusable out_path (tpu_watch) must never let a PREVIOUS cycle's
        # artifact pass as a fresh capture when the subprocess dies before
        # writing
        os.remove(out_path)
    env = dict(os.environ)
    env.pop("TPUFT_BENCH_PLATFORM", None)
    env["TPUFT_BENCH_SKIP_FLEET"] = "1"
    # the recapture's sole job is TPU phase A: the degraded drills are
    # platform-independent and already ran (or will) in the parent
    env["TPUFT_BENCH_SKIP_DEGRADED"] = "1"
    env["TPUFT_BENCH_OUT"] = out_path
    env["TPUFT_BENCH_REPROBE_WINDOW_S"] = "0"  # no recursive recovery
    env["TPUFT_BENCH_PROBE_WINDOW_S"] = str(probe_window_s)
    try:
        subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=sys.stderr,
            timeout=budget_s,
            check=False,
        )
    except subprocess.TimeoutExpired:
        # the child often finishes the artifact and only wedges at jax
        # teardown (the TPU tunnel); the stale-artifact pre-delete above
        # makes reading after a timeout safe, so still try the file — the
        # platform/single checks below validate whatever landed
        log(
            f"phase-A subprocess exceeded its {budget_s:.0f}s budget; "
            "checking for a finished artifact anyway"
        )
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        log(f"phase-A capture failed: {e}")
        return None
    try:
        with open(out_path) as f:
            artifact = json.load(f)
    except Exception as e:  # noqa: BLE001 — capture is best-effort
        log(f"phase-A capture failed: {e}")
        return None
    single = artifact.get("single") or {}
    # cpu_fallback alone is not enough: a tunnel that fails FAST (instead
    # of hanging) resolves jax to the CPU platform, the probe's plain
    # matmul passes, and a CPU artifact would masquerade as a TPU capture
    if not single or artifact.get("cpu_fallback") or single.get("platform") != "tpu":
        log(
            "phase-A capture is not a TPU artifact "
            f"(platform={single.get('platform')!r}); discarding"
        )
        return None
    return artifact


def _try_tpu_phase_a(
    max_total_s: Optional[float] = None,
    log=lambda m: print(f"bench: {m}", file=sys.stderr),
):
    """Mid-run tunnel recovery (round-4 verdict item 1b): after a CPU
    fallback, re-probe the TPU briefly and — when the tunnel healed while
    the CPU phases ran — capture a REAL phase-A artifact in a fresh
    subprocess (this process's jax backend is already pinned to CPU and
    cannot be re-targeted).  Returns the subprocess's phase-A ``single``
    dict, or None."""
    from torchft_tpu.utils.probe import backend_executes_with_retries

    window = float(os.environ.get("TPUFT_BENCH_REPROBE_WINDOW_S", "60"))
    if window <= 0:
        return None
    budget = float(os.environ.get("TPUFT_BENCH_REPROBE_BUDGET_S", "1500"))
    probe_timeout = float(os.environ.get("TPUFT_BENCH_PROBE_TIMEOUT_S", "180"))
    if max_total_s is not None:
        # the recovery must not push the run past the total wall-clock
        # budget — overrunning is exactly the lost-final-line failure the
        # budget exists to prevent.  The probe's LAST attempt can run past
        # the window by a full probe timeout, so reserve that too.
        if max_total_s < window + probe_timeout + 240.0:
            log(
                f"skipping TPU recovery: {max_total_s:.0f}s of total budget "
                "left (< probe window + probe timeout + minimum capture)"
            )
            return None
    log(f"re-probing TPU backend for {window:.0f}s (mid-run recovery)")
    t_probe = time.time()
    if not backend_executes_with_retries(
        window_s=window,
        timeout_s=probe_timeout,
        log=log,
    ):
        log("re-probe failed; keeping the CPU artifact")
        return None
    if max_total_s is not None:
        # clamp to what probing actually left over, minus an emit/teardown
        # margin — the parent still prints the headline AFTER the capture
        budget = min(budget, max_total_s - (time.time() - t_probe) - 60.0)
        if budget < 180.0:
            log("skipping TPU recovery: probe consumed the budget")
            return None
    log("TPU healthy on re-probe: running phase A in a subprocess")
    artifact = capture_phase_a_subprocess(budget_s=budget, log=log)
    return artifact.get("single") if artifact else None


def main() -> None:
    # total wall-clock budget: a driver that kills a long bench would
    # capture NO final JSON line at all, so the bench bounds itself and
    # prints whatever phases completed (the streaming bench_out.json plus
    # this guarantee = an artifact on every path).  The initial probe
    # counts against the budget, but a wedged tunnel (900 s probe window)
    # must not starve the measurement phases into a degraded artifact on
    # exactly the runs where the CPU numbers are all there is — so the
    # phases keep a floor (default 1500 s) and the hard worst case is
    # probe window + floor (~40 min at defaults).
    budget_s = float(os.environ.get("TPUFT_BENCH_TOTAL_BUDGET_S", "2100"))
    phase_floor_s = float(os.environ.get("TPUFT_BENCH_PHASE_FLOOR_S", "1500"))
    t_probe_start = time.time()
    t_start = t_probe_start
    # hard self-deadline: covers the probe window + the phase floor with
    # margin; MUST fire before any external `timeout` wrapper so the round
    # always ends with a parseable artifact + headline instead of rc=124
    hard_deadline_env = os.environ.get("TPUFT_BENCH_HARD_DEADLINE_S", "")
    hard_deadline_s = float(hard_deadline_env or budget_s + 1200.0)
    watchdog = None
    if hard_deadline_s > 0:
        watchdog = _install_hard_deadline(t_probe_start + hard_deadline_s)

    def remaining_s() -> float:
        return budget_s - (time.time() - t_start)

    platform = os.environ.get("TPUFT_BENCH_PLATFORM")
    fallback = False
    if not platform and not _probe_backend_with_retries():
        fallback = True
        banner = "!" * 72
        print(
            f"{banner}\n"
            "bench: CPU FALLBACK — the default jax backend (TPU tunnel) "
            "failed to\ninitialize within the retry window.  EVERY NUMBER "
            "BELOW IS A CPU\nMEASUREMENT, NOT TPU.\n"
            f"{banner}",
            file=sys.stderr,
        )
        platform = "cpu"
    # probe done: charge it to the budget; the floor only compensates for
    # probe time actually spent and never raises an explicitly smaller
    # budget (a caller sizing a kill timeout to its env value must win)
    budget_s = max(
        min(phase_floor_s, budget_s),
        budget_s - (time.time() - t_probe_start),
    )
    t_start = time.time()
    if watchdog is not None and not hard_deadline_env:
        # probe resolved: re-arm the watchdog TIGHT against the remaining
        # budget (one straddling phase floor + teardown of margin) instead
        # of the install-time worst case that had to cover a 900 s wedged
        # probe.  The round-5 rc=124 fired in exactly that slack: probe
        # done in seconds, legs overran, external timeout < install-time
        # deadline.  Never re-arm LATER than the install-time deadline (a
        # slow-but-successful probe would otherwise push past the bound
        # drivers sized their kill timeouts to); an explicit
        # TPUFT_BENCH_HARD_DEADLINE_S is honored verbatim.
        watchdog.cancel()
        watchdog = _install_hard_deadline(
            min(
                t_probe_start + hard_deadline_s,
                t_start + budget_s + 420.0,
            )
        )
    _configure_jax(platform)

    import jax

    on_cpu = jax.default_backend() == "cpu"
    sizes = _sizes(on_cpu)
    _emit_partial(
        platform=jax.default_backend(),
        cpu_fallback=fallback,
        sizes={k: v for k, v in sizes.items()},
    )

    single = run_single(sizes)
    _emit_partial(single=single)

    faults: Dict[str, Any] = {}
    diloco: Dict[str, Any] = {}
    ratio = None
    skip_fleet = bool(os.environ.get("TPUFT_BENCH_SKIP_FLEET"))
    if not skip_fleet and remaining_s() < 60.0:
        # budget already exhausted (probe + phase A ran long): skipping
        # beats stacking the 120/180 s fleet floors past the stated budget
        skip_fleet = True
        faults = {
            "note": (
                f"fleet phases skipped: total budget exhausted "
                f"({remaining_s():.0f}s left of {budget_s:.0f}s)"
            )
        }
    if not skip_fleet:
        fleet_deadline_ts = t_start + budget_s
        worker_platform = "cpu" if on_cpu else None
        replicas = max(2, sizes["replicas"])
        faultfree = run_fleet(
            "faultfree",
            target_steps=max(10, sizes["fleet_steps"] // 3),
            sizes=sizes,
            worker_platform=worker_platform,
            replicas=replicas,
            deadline_s=_budget_left(fleet_deadline_ts, 0.25, 120.0),
        )
        print(f"bench: fleet fault-free {faultfree}", file=sys.stderr)
        _emit_partial(faultfree_fleet=faultfree)
        faulted = run_fleet(
            "faults",
            target_steps=sizes["fleet_steps"],
            sizes=sizes,
            worker_platform=worker_platform,
            kill_every=sizes["kill_every"],
            replicas=replicas,
            deadline_s=_budget_left(fleet_deadline_ts, 0.55, 180.0),
        )
        print(f"bench: fleet with faults {faulted}", file=sys.stderr)
        _emit_partial(faulted_fleet=faulted)
        faults = {
            "fleet_steps": sizes["fleet_steps"],
            "kill_every": sizes["kill_every"],
            "replicas": replicas,
            "standby": bool(sizes.get("standby")),
            "kills": faulted.get("kills", 0),
            "faultfree_fleet": faultfree,
            "faulted_fleet": faulted,
        }
        for k in ("mean_heal_in_s", "heal_breakdown"):
            if faulted.get(k) is not None:
                faults[k] = faulted[k]
        ratio = faulted.get("ratio_per_100step_kill")

        if not os.environ.get("TPUFT_BENCH_SKIP_DILOCO"):
            if remaining_s() > 240.0:
                diloco = _run_diloco_phase(
                    sizes,
                    worker_platform,
                    replicas,
                    deadline_ts=t_start + budget_s,
                )
            else:
                diloco = {
                    "skipped": (
                        f"total budget exhausted ({remaining_s():.0f}s left "
                        f"of {budget_s:.0f}s); raise TPUFT_BENCH_TOTAL_BUDGET_S"
                    )
                }
            _emit_partial(diloco=diloco)

        if not os.environ.get("TPUFT_BENCH_SKIP_SPARE"):
            # hot-spare promotion gate (thread plane, wan_1g): cheap —
            # seconds, not minutes — so it only needs a token budget floor
            if remaining_s() > 30.0:
                spare_promotion = _run_spare_phase()
            else:
                spare_promotion = {
                    "skipped": f"budget exhausted ({remaining_s():.0f}s left)"
                }
            print(f"bench: spare promotion {spare_promotion}", file=sys.stderr)
            _emit_partial(spare_promotion=spare_promotion)
            faults["spare_promotion"] = spare_promotion

    if not os.environ.get("TPUFT_BENCH_SKIP_DEGRADED"):
        # degraded-mode gate (thread plane, wan_1g): independent of the
        # fleet phases (it drives its own drill fleet), so it runs — or
        # records why it didn't — even when the fleet block is skipped;
        # like the spare phase it costs seconds, so a token budget floor
        # suffices
        if remaining_s() > 30.0:
            degraded = _run_degraded_phase()
        else:
            degraded = {
                "skipped": f"budget exhausted ({remaining_s():.0f}s left)"
            }
        print(f"bench: degraded {degraded}", file=sys.stderr)
        # the two degraded headline keys stream as TOP-LEVEL partial
        # keys the moment the phase lands, so a watchdog trip still
        # reports them (the BENCH_r05 lesson)
        _emit_partial(
            degraded=degraded,
            degraded_step_time_ratio=degraded.get(
                "degraded_step_time_ratio"
            ),
            wound_to_swap_s=degraded.get("wound_to_swap_s"),
        )
        faults["degraded"] = degraded

    coord: Dict[str, Any] = {}
    if not os.environ.get("TPUFT_BENCH_SKIP_COORD"):
        if remaining_s() > 60.0:
            coord = _run_coord_phase(
                int(
                    os.environ.get("TPUFT_BENCH_COORD_REPLICAS", 0)
                    or (120 if on_cpu else 500)
                )
            )
        else:
            coord = {
                "skipped": f"budget exhausted ({remaining_s():.0f}s left)"
            }
        print(f"bench: coord {coord}", file=sys.stderr)
        # the two coordination headline keys stream as TOP-LEVEL partial
        # keys the moment the phase lands, so a watchdog trip still
        # reports them (the BENCH_r05 lesson)
        _emit_partial(
            coord=coord,
            coord_p99_quorum_latency_s=coord.get("p99_quorum_latency_s"),
            lighthouse_cpu_frac=coord.get("lighthouse_cpu_frac"),
        )

    obs: Dict[str, Any] = {}
    if not os.environ.get("TPUFT_BENCH_SKIP_OBS"):
        # observability-overhead gate (ISSUE 14): pure host-side micro
        # phase, seconds regardless of platform — runs even when the fleet
        # block was skipped
        try:
            obs = _run_obs_phase()
        except Exception as e:  # noqa: BLE001 — a failed phase is a
            # recorded fact, never a lost artifact
            obs = {"error": f"{type(e).__name__}: {e}"}
        print(f"bench: obs overhead {obs}", file=sys.stderr)
        # the headline key streams TOP-LEVEL the moment the phase lands
        _emit_partial(
            obs=obs, obs_overhead_frac=obs.get("obs_overhead_frac")
        )

    if ratio is None:
        # fleet phases unusable: fall back to the ws=1 protocol ratio so the
        # bench always reports something honest
        ratio = single["ws1_ratio"]
        faults.setdefault("note", "fleet phases incomplete; value is ws=1 ratio")
        metric = "ft_vs_faultfree_tokens_per_sec_ratio"
    else:
        # BASELINE's contract: sustained throughput under one replica kill
        # per 100 steps, measured from the survivor's steady step time and
        # the per-kill disruption overhead (see _fleet_metrics)
        metric = "ft_withfaults_vs_faultfree_tokens_per_sec_ratio_100step_kill"

    # mid-run recovery: a CPU-fallback run still grabs a TPU phase A when
    # the tunnel heals while the CPU phases were running
    single_tpu: Optional[Dict[str, Any]] = None
    if fallback:
        single_tpu = _try_tpu_phase_a(max_total_s=remaining_s())
        if single_tpu:
            _emit_partial(single_tpu=single_tpu)

    qdr_active, qdr_reason = _quant_device_reduce_active()
    out = {
        "metric": metric,
        "value": round(ratio, 4),
        "unit": "ratio",
        "vs_baseline": round(ratio / 0.95, 4),
        # which quantized-allreduce reduction path this env would run
        # (device Pallas dequant-sum-requant vs host): recorded because the
        # tunnel auto-gates the device path off (benchmarks/RESULTS.md)
        "quant_device_reduce": qdr_active,
        "quant_device_reduce_reason": qdr_reason,
        **single,
    }
    if faults:
        out["faults"] = faults
        if "mean_heal_in_s" in faults:
            out["mean_heal_in_s"] = faults["mean_heal_in_s"]
    if diloco:
        out["diloco"] = diloco
    if coord:
        out["coord"] = coord
    if obs:
        out["obs"] = obs
    if single_tpu:
        out["single_tpu"] = single_tpu
    # FULL detail goes to bench_out.json; stdout gets ONE compact headline
    # object (<= ~1 KB) as the LAST line, so a driver that captures only a
    # 2000-char output tail always holds one complete parseable JSON
    # (rounds 3 AND 4 lost the artifact head to exactly that truncation)
    _emit_partial(final=out)
    headline = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": "ratio",
        "vs_baseline": out["vs_baseline"],
        "platform": single.get("platform"),
        "device_kind": single.get("device_kind"),
        "cpu_fallback": fallback,
        "tier": single.get("tier"),
        "mfu": single.get("mfu"),
        "mfu_ft": single.get("mfu_ft"),
        "model_tflops_per_sec": single.get("model_tflops_per_sec"),
        "faultfree_tokens_per_sec": single.get("faultfree_tokens_per_sec"),
        "ws1_ratio": single.get("ws1_ratio"),
        "remat": single.get("remat"),
        "mean_heal_in_s": out.get("mean_heal_in_s"),
        "heal_in_s_by_path": (faults.get("faulted_fleet") or {}).get(
            "heal_in_s_by_path"
        ),
        # heal_breakdown phases as top-level keys (round-over-round
        # comparable without opening bench_out.json), and the hot-spare
        # promotion heal-in NEXT TO the cold fleet heal-in — the PR-6
        # payoff measured side by side
        **_headline_heal_keys(faults),
        "spare_mean_heal_in_s": (faults.get("spare_promotion") or {}).get(
            "mean_heal_in_s"
        ),
        "spare_warm_lag_steps": (faults.get("spare_promotion") or {}).get(
            "warm_lag_steps"
        ),
        "kills": faults.get("kills"),
        "diloco_ratio": diloco.get("ratio_per_100step_kill"),
        "diloco_kills": diloco.get("kills_in_sync_window"),
        # PR-5 trajectory: outer sync cost, sharded vs replicated
        "sync_overhead_s_sharded": diloco.get("sync_overhead_s_sharded"),
        "sync_overhead_s_replicated": diloco.get("sync_overhead_s_replicated"),
        # ISSUE-15 streamed outer sync: residual barrier cost, overlap
        # win, and the fraction-of-an-inner-step headline (§18 gate 0.05)
        "sync_overhead_s_streaming": diloco.get("sync_overhead_s_streaming"),
        "stream_overlap_ratio": diloco.get("stream_overlap_ratio"),
        "sync_overhead_frac": diloco.get("sync_overhead_frac"),
        # ISSUE-12 coordination plane: quorum latency through churn at
        # scale, lighthouse CPU, and the aggregation RPC win
        "coord_p99_quorum_latency_s": coord.get("p99_quorum_latency_s"),
        "lighthouse_cpu_frac": coord.get("lighthouse_cpu_frac"),
        "coord_rpc_reduction": coord.get("rpc_reduction_vs_direct"),
        # ISSUE-14 observability plane: recorder+spans fully enabled must
        # cost <= 1% step time (the obs phase's measured fraction)
        "obs_overhead_frac": obs.get("obs_overhead_frac"),
        "quant_device_reduce": qdr_active,
        "detail": "bench_out.json",
    }
    if single_tpu:
        # the recovered TPU phase A carries the north-star MFU; the fleet
        # ratio above remains the CPU measurement (labeled by cpu_fallback)
        headline["tpu_recovered"] = True
        headline["mfu"] = single_tpu.get("mfu")
        headline["mfu_ft"] = single_tpu.get("mfu_ft")
        headline["model_tflops_per_sec"] = single_tpu.get(
            "model_tflops_per_sec"
        )
        headline["device_kind"] = single_tpu.get("device_kind")
        headline["remat"] = single_tpu.get("remat")
    blob = json.dumps(headline)
    if len(blob) > 1900:  # belt-and-braces: never outgrow a tail capture
        for k in (
            "heal_in_s_by_path",
            "remat",
            "ws1_ratio",
            "tier",
            "heal_respawn_s",
            "heal_join_s",
            "heal_transfer_s",
            "heal_first_commit_s",
            "heal_promote_s",
            "spare_warm_lag_steps",
        ):
            headline.pop(k, None)
        blob = json.dumps(headline)
    print(blob)


def _quant_device_reduce_active() -> Tuple[bool, str]:
    """(active, reason) for the Pallas dequant-sum-requant path at a 1 MB
    shard.  Recorded in the artifact because the axon debug tunnel turns
    every H2D/D2H into a network round trip, making the device reduce a
    net loss there even though it wins on locally-attached chips
    (benchmarks/RESULTS.md)."""
    import jax

    from torchft_tpu.collectives import DEVICE_REDUCE_ENV, _use_device_reduce

    active = bool(_use_device_reduce(1 << 20))
    mode = os.environ.get(DEVICE_REDUCE_ENV, "")
    if mode == "0":
        return active, "forced off via env"
    if mode == "1":
        return active, "forced on via env"
    if jax.default_backend() != "tpu":
        return active, "off: backend is not tpu"
    return active, "auto (tpu backend, >=256KiB shards)"


def _budget_left(
    deadline_ts: Optional[float], frac: float, floor: float
) -> Optional[float]:
    """A fleet's share of what's left of the phase budget (None = no
    bound) — one policy for the fault-free and churn fleets alike.

    The floor keeps a phase viable when an earlier phase ran long, but only
    spends budget that actually remains: once the deadline is near/past the
    phase is capped at what is left (a token 30 s minimum), so stacked
    floors can no longer push total wall clock minutes past
    TPUFT_BENCH_TOTAL_BUDGET_S — the r05 bench exited rc=124 to exactly
    that.  Worst-case overrun is now the one phase that straddles the
    deadline (<= its own floor) plus teardown; drivers should size kill
    timeouts to budget + 180 s + margin.
    """
    if deadline_ts is None:
        return None
    remaining = deadline_ts - time.time()
    if remaining <= 30.0:
        return 30.0
    return max(min(floor, remaining), remaining * frac)


def _run_diloco_phase(
    sizes: Dict[str, int],
    worker_platform: Optional[str],
    replicas: int,
    deadline_ts: Optional[float] = None,
) -> Dict[str, Any]:
    """Phase D: Streaming DiLoCo islands, fault-free vs churn with kills
    timed into the fragment-sync window (BASELINE config 4).

    The quantized pseudogradient wire is gated on MEASURED benefit: in the
    default "auto" mode the fault-free fleet runs once per wire (f32 and
    int8/fp8), both sync overheads are recorded, and the churn run uses the
    wire that costs less per sync on this link — quantization spends host
    cycles that a fat loopback never repays, while over a thin DCN the 4x
    payload cut wins (the reference keeps quantization opt-in for the same
    reason, ``torchft/manager.py:457-468``)."""
    mode = _diloco_quant_env()
    ff_target = max(12, sizes["diloco_steps"] // 2)

    def _faultfree(tag: str, quant: bool) -> Dict[str, Any]:
        r = run_fleet(
            f"diloco_faultfree_{tag}",
            target_steps=ff_target,
            sizes=sizes,
            worker_platform=worker_platform,
            replicas=replicas,
            mode="diloco",
            extra_env={"TPUFT_BENCH_DILOCO_QUANT_WIRE": "1" if quant else "0"},
            deadline_s=_budget_left(deadline_ts, 0.25, 90.0),
        )
        print(f"bench: diloco fault-free [{tag}] {r}", file=sys.stderr)
        # stream EVERY sub-leg into the artifact the moment it lands: the
        # round-5 loss was per-scenario numbers that existed only on
        # stderr when the run died between diloco legs
        _emit_partial(**{f"diloco_faultfree_{tag}": r})
        return r

    ff_by_wire: Dict[str, Dict[str, Any]] = {}
    if mode == "auto":
        ff_by_wire["f32"] = _faultfree("f32", quant=False)
        budget_left = (
            None if deadline_ts is None else deadline_ts - time.time()
        )
        if budget_left is not None and budget_left < 360.0:
            # starve the A/B before the churn run, never the reverse — the
            # churn ratio is the phase's headline number
            faultfree = ff_by_wire["f32"]
            use_quant = False
            gate = "auto"
            gate_reason = (
                f"quant A/B skipped: {budget_left:.0f}s of budget left is "
                "reserved for the churn run"
            )
            return _diloco_churn_and_summary(
                sizes, worker_platform, replicas, deadline_ts,
                ff_by_wire, faultfree, use_quant, gate, gate_reason,
            )
        ff_by_wire["quant"] = _faultfree("quant", quant=True)
        so_f = ff_by_wire["f32"].get("sync_overhead_s")
        so_q = ff_by_wire["quant"].get("sync_overhead_s")
        # use the quantized wire when it is at least as cheap per sync
        # (within 10% counts: the payload cut is worth noise-level host tax)
        if so_f is not None and so_q is not None:
            use_quant = so_q <= so_f * 1.1
            gate_reason = f"measured: quant {so_q}s vs f32 {so_f}s per sync"
        else:
            use_quant = False
            gate_reason = (
                "gate fell back to f32: sync_overhead_s missing "
                f"(quant={so_q}, f32={so_f}) — too few committed sync steps"
            )
        gate = "auto"
    else:
        use_quant = mode == "1"
        ff_by_wire["quant" if use_quant else "f32"] = _faultfree(
            "quant" if use_quant else "f32", quant=use_quant
        )
        gate = "forced"
        gate_reason = f"TPUFT_BENCH_DILOCO_QUANT={mode}"
    faultfree = ff_by_wire["quant" if use_quant else "f32"]
    # sharded-vs-replicated sync overhead (docs/operations.md §11): one
    # extra fault-free leg pins TORCHFT_OUTER_SHARD=0 (the legacy
    # replicated outer update) on the chosen wire, so the PR-5 perf
    # trajectory is machine-readable in the artifact round over round.
    # Budget-guarded like the quant A/B — the churn run is the phase's
    # headline and is never starved for this row.
    budget_left = None if deadline_ts is None else deadline_ts - time.time()
    if _outer_shard_mode_env() != "0" and (
        budget_left is None or budget_left >= 360.0
    ):
        # when the session itself pins the legacy path the main legs ARE
        # replicated — an extra pinned leg would be a meaningless A/A burn
        ff_by_wire["replicated"] = run_fleet(
            "diloco_faultfree_replicated",
            target_steps=ff_target,
            sizes=sizes,
            worker_platform=worker_platform,
            replicas=replicas,
            mode="diloco",
            extra_env={
                "TPUFT_BENCH_DILOCO_QUANT_WIRE": "1" if use_quant else "0",
                "TORCHFT_OUTER_SHARD": "0",
            },
            deadline_s=_budget_left(deadline_ts, 0.25, 90.0),
        )
        print(
            f"bench: diloco fault-free [replicated] "
            f"{ff_by_wire['replicated']}",
            file=sys.stderr,
        )
        _emit_partial(diloco_faultfree_replicated=ff_by_wire["replicated"])
    # ISSUE-15 streamed outer sync (docs/operations.md §18): one more leg
    # on the chosen wire with the fragment scheduler forced on, so the
    # artifact carries blocking-vs-streamed residual sync cost round over
    # round.  Budget-guarded like the other A/B rows — churn is never
    # starved for it — and TPUFT_BENCH_SKIP_STREAM=1 opts out.
    budget_left = None if deadline_ts is None else deadline_ts - time.time()
    per_frag = max(
        1, sizes["diloco_sync_every"] // max(1, sizes["diloco_fragments"])
    )
    stall_room = per_frag - sizes["diloco_sync_delay"] - 1
    if (
        not os.environ.get("TPUFT_BENCH_SKIP_STREAM")
        and stall_room >= 1
        and (budget_left is None or budget_left >= 360.0)
    ):
        ff_by_wire["streaming"] = run_fleet(
            "diloco_faultfree_streaming",
            target_steps=ff_target,
            sizes=sizes,
            worker_platform=worker_platform,
            replicas=replicas,
            mode="diloco",
            extra_env={
                "TPUFT_BENCH_DILOCO_QUANT_WIRE": "1" if use_quant else "0",
                "TORCHFT_STREAM_SYNC": "1",
                "TORCHFT_STREAM_MAX_STALENESS": str(stall_room),
            },
            deadline_s=_budget_left(deadline_ts, 0.25, 90.0),
        )
        print(
            f"bench: diloco fault-free [streaming] "
            f"{ff_by_wire['streaming']}",
            file=sys.stderr,
        )
        # the BENCH_r05 lesson: stream the leg into the partial artifact
        # the moment it lands, never only into the final assembly
        _emit_partial(diloco_faultfree_streaming=ff_by_wire["streaming"])
    elif not os.environ.get("TPUFT_BENCH_SKIP_STREAM") and stall_room < 1:
        print(
            "bench: diloco streaming leg skipped — cadence has no "
            f"staleness room (per_frag={per_frag}, "
            f"delay={sizes['diloco_sync_delay']})",
            file=sys.stderr,
        )
    return _diloco_churn_and_summary(
        sizes, worker_platform, replicas, deadline_ts,
        ff_by_wire, faultfree, use_quant, gate, gate_reason,
    )


def _diloco_churn_and_summary(
    sizes: Dict[str, int],
    worker_platform: Optional[str],
    replicas: int,
    deadline_ts: Optional[float],
    ff_by_wire: Dict[str, Dict[str, Any]],
    faultfree: Dict[str, Any],
    use_quant: bool,
    gate: str,
    gate_reason: str,
) -> Dict[str, Any]:
    """Churn run + phase-D artifact assembly, shared by the full A/B path
    and the budget-starved early path."""
    churn = run_fleet(
        "diloco_churn",
        target_steps=sizes["diloco_steps"],
        sizes=sizes,
        worker_platform=worker_platform,
        replicas=replicas,
        mode="diloco",
        kill_every=max(
            sizes["diloco_sync_every"],
            sizes["diloco_steps"] // (sizes["diloco_kills"] + 1),
        ),
        kill_in_sync_window=True,
        max_kills=sizes["diloco_kills"],
        extra_env={"TPUFT_BENCH_DILOCO_QUANT_WIRE": "1" if use_quant else "0"},
        deadline_s=_budget_left(deadline_ts, 0.9, 180.0),
    )
    print(f"bench: diloco churn {churn}", file=sys.stderr)
    _emit_partial(diloco_churn=churn)
    out: Dict[str, Any] = {
        "sync_every": sizes["diloco_sync_every"],
        "fragments": sizes["diloco_fragments"],
        "fragment_sync_delay": sizes["diloco_sync_delay"],
        "quantized_sync": use_quant,
        "quant_gate": gate,
        "quant_gate_reason": gate_reason,
        "quant_kind": _quant_kind_or_error(),
        "kills_in_sync_window": churn.get("kills", 0),
        "faultfree": faultfree,
        "churn": churn,
    }
    # the alternate wire's fleet run is never discarded: both runs (and
    # whatever overheads they produced) land in the artifact even when the
    # gate had to fall back
    alt_wire = "f32" if use_quant else "quant"
    if alt_wire in ff_by_wire:
        out["faultfree_alt"] = ff_by_wire[alt_wire]
    for wire, r in ff_by_wire.items():
        if r.get("sync_overhead_s") is not None:
            out[f"sync_overhead_s_{wire}"] = r["sync_overhead_s"]
    # the f32/quant legs run with the session's TORCHFT_OUTER_SHARD
    # (default auto = sharded); the "replicated" leg pinned =0.  Emit the
    # chosen wire's overhead under a stable "sharded" name next to the
    # replicated row so BENCH artifacts compare like for like.
    shard_mode = _outer_shard_mode_env()
    out["outer_shard_mode"] = shard_mode
    if faultfree.get("sync_overhead_s") is not None:
        if shard_mode != "0":
            out["sync_overhead_s_sharded"] = faultfree["sync_overhead_s"]
        else:
            # pinned-legacy session: the chosen wire's leg ran replicated
            out.setdefault(
                "sync_overhead_s_replicated", faultfree["sync_overhead_s"]
            )
    so_r = out.get("sync_overhead_s_replicated")
    so_s = out.get("sync_overhead_s_sharded")
    if so_r is not None and so_s is not None:
        out["sharded_vs_replicated_sync_overhead"] = round(
            so_r / max(so_s, 1e-4), 3
        )
    # ISSUE-15 streamed outer sync: the residual barrier cost, how much of
    # the blocking sync it hid, and the headline fraction of an inner step
    # the residual represents (the §18 gate is <= 0.05 under wan_1g)
    stream_leg = ff_by_wire.get("streaming")
    so_stream = out.get("sync_overhead_s_streaming")
    if so_stream is not None:
        blocking = so_s if so_s is not None else so_r
        if blocking is not None and blocking > 1e-4:
            out["stream_overlap_ratio"] = round(
                min(1.0, max(0.0, 1.0 - so_stream / blocking)), 3
            )
        inner_s = stream_leg.get("inner_step_s") or stream_leg.get(
            "t_step_s"
        )
        if inner_s:
            out["sync_overhead_frac"] = round(
                so_stream / max(float(inner_s), 1e-6), 4
            )
    if "sync_overhead_s_f32" in out and "sync_overhead_s_quant" in out:
        base = max(out["sync_overhead_s_f32"], 1e-4)
        out["quant_vs_f32_sync_overhead"] = round(
            out["sync_overhead_s_quant"] / base, 3
        )
    tf = faultfree.get("t_step_s")
    tc = churn.get("t_step_s")
    if tf and tc:
        out["inner_step_ratio"] = round(tf / tc, 4)
    if faultfree.get("sync_overhead_s") is not None:
        out["sync_overhead_s"] = faultfree["sync_overhead_s"]
    if churn.get("ratio_per_100step_kill") is not None:
        out["ratio_per_100step_kill"] = churn["ratio_per_100step_kill"]
    if churn.get("mean_heal_in_s") is not None:
        out["mean_heal_in_s"] = churn["mean_heal_in_s"]
    return out


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker_main()
    else:
        main()
