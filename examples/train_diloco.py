"""Streaming DiLoCo training example (BASELINE config 4, reference
``train_diloco.py``).

Each replica group ("island") trains locally with an inner optimizer and
synchronizes averaged pseudogradients through an outer optimizer every
``--sync-every`` steps, with the model split into fragments whose syncs are
staggered and overlapped (Streaming DiLoCo).  Communication cost over DCN is
O(model/sync_every), which is what makes cross-datacenter training viable.

    python -m torchft_tpu.lighthouse --min_replicas 2 --bind 0.0.0.0:29520 &
    TORCHFT_LIGHTHOUSE=localhost:29520 REPLICA_GROUP_ID=0 python examples/train_diloco.py &
    TORCHFT_LIGHTHOUSE=localhost:29520 REPLICA_GROUP_ID=1 python examples/train_diloco.py
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchft_tpu.tier import default_tier, make_communicator, manager_server_cls
from torchft_tpu.local_sgd import DiLoCo
from torchft_tpu.manager import Manager
from torchft_tpu.optim import OptimizerWrapper  # noqa: F401 (inner loop is plain optax)

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("train_diloco")


def _mlp_init(key, sizes):
    params = {}
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params[f"layer_{i}"] = {
            "w": jax.random.normal(sub, (fan_in, fan_out)) * np.sqrt(2.0 / fan_in),
            "b": jnp.zeros(fan_out),
        }
    return params


def _mlp_apply(params, x):
    n = len(params)
    for i in range(n):
        p = params[f"layer_{i}"]
        x = x @ p["w"] + p["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--total-syncs", type=int, default=10)
    parser.add_argument("--sync-every", type=int, default=8)
    parser.add_argument("--num-fragments", type=int, default=2)
    parser.add_argument("--fragment-sync-delay", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument(
        "--replica-group-id",
        type=int,
        default=int(os.environ.get("REPLICA_GROUP_ID", 0)),
    )
    parser.add_argument("--min-replicas", type=int, default=2)
    parser.add_argument(
        "--quantize",
        action="store_true",
        help="1-byte pseudogradient sync (int8 default, fp8 via "
        "TORCHFT_QUANT_KIND) — the reference's DiLoCo wire",
    )
    parser.add_argument("--platform", default=None)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(4096, 64)).astype(np.float32)
    w_true = rng.normal(size=(64, 8)).astype(np.float32)
    y = x @ w_true + 0.01 * rng.normal(size=(4096, 8)).astype(np.float32)

    params = _mlp_init(jax.random.PRNGKey(0), [64, 128, 128, 8])
    inner_tx = optax.adamw(3e-4)
    holder = {"params": params}
    inner_state = inner_tx.init(params)

    tier = default_tier()  # C++ plane when native/libtpuft.so loads
    manager = Manager(
        comm=make_communicator(timeout_s=60.0),  # data-plane tier dispatch
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=args.min_replicas,
        use_async_quorum=False,  # DiLoCo requires a synchronous quorum
        replica_id=f"train_diloco_{args.replica_group_id}",
        quorum_timeout=120.0,
        server_cls=manager_server_cls(tier),
    )
    diloco = DiLoCo(
        manager,
        holder,
        outer_tx=optax.sgd(0.7, momentum=0.9, nesterov=True),
        sync_every=args.sync_every,
        num_fragments=args.num_fragments,
        fragment_sync_delay=args.fragment_sync_delay,
        should_quantize=args.quantize,
    )

    def loss_fn(p, batch):
        bx, by = batch
        pred = _mlp_apply(p, bx)
        return jnp.mean((pred - by) ** 2)

    loss_and_grad = jax.jit(jax.value_and_grad(loss_fn))

    syncs = 0
    step = 0
    with diloco:
        while syncs < args.total_syncs:
            idx = rng.integers(0, len(x), size=args.batch_size)
            batch = (jnp.asarray(x[idx]), jnp.asarray(y[idx]))
            loss, grads = loss_and_grad(holder["params"], batch)
            updates, inner_state = inner_tx.update(
                grads, inner_state, holder["params"]
            )
            holder["params"] = optax.apply_updates(holder["params"], updates)
            step += 1
            result = diloco.step()
            if result is not None:
                syncs += 1
                logger.info(
                    "sync %d at inner step %d committed=%s loss %.5f",
                    syncs,
                    step,
                    result,
                    float(loss),
                )

    leaves = jax.tree_util.tree_leaves(holder["params"])
    digest = hashlib.sha256()
    for leaf in leaves:
        digest.update(np.ascontiguousarray(np.asarray(leaf, dtype=np.float32)))
    print(f"FINAL syncs={syncs} params_sha={digest.hexdigest()[:16]}")
    manager.shutdown()


if __name__ == "__main__":
    main()
