"""Fault-tolerant data-parallel training example (BASELINE config 1).

The torchft_tpu analog of the reference's ``train_ddp.py``: an ordinary
jax/optax train loop on a toy CNN where fault tolerance is two extra verbs —
``opt.start_step()`` and ``opt.step()`` — plus a gradient allreduce.  Run one
process per replica group::

    python -m torchft_tpu.lighthouse --min_replicas 1 --bind 0.0.0.0:29510 &
    TORCHFT_LIGHTHOUSE=localhost:29510 REPLICA_GROUP_ID=0 python examples/train_ddp.py &
    TORCHFT_LIGHTHOUSE=localhost:29510 REPLICA_GROUP_ID=1 python examples/train_ddp.py &

Kill any replica mid-run and restart it: it heals from a healthy peer's live
weights and training continues without a global restart.
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchft_tpu.tier import default_tier, make_communicator, manager_server_cls
from torchft_tpu.data import DistributedSampler, batch_indices
from torchft_tpu.ddp import ft_allreduce
from torchft_tpu.manager import Manager
from torchft_tpu.models.cnn import SimpleCNN
from torchft_tpu.optim import OptimizerWrapper

logging.basicConfig(
    level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
)
logger = logging.getLogger("train_ddp")


def synthetic_cifar(n: int = 2048, seed: int = 0):
    """Deterministic synthetic CIFAR-10-shaped dataset (no downloads in a
    zero-egress environment)."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument(
        "--replica-group-id",
        type=int,
        default=int(os.environ.get("REPLICA_GROUP_ID", 0)),
    )
    parser.add_argument(
        "--num-replica-groups",
        type=int,
        default=int(os.environ.get("NUM_REPLICA_GROUPS", 2)),
    )
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument(
        "--comm-timeout",
        type=float,
        default=30.0,
        help="per-op userspace timeout; a wedged peer is evicted after this",
    )
    parser.add_argument(
        "--step-time",
        type=float,
        default=0.0,
        help="minimum seconds per step (paces chaos-test scenarios)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        help="force a jax platform (e.g. cpu) — useful when several replica "
        "processes share one host",
    )
    args = parser.parse_args()

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    x, y = synthetic_cifar()
    model = SimpleCNN(num_classes=10)
    params = model.init(jax.random.PRNGKey(0))
    tx = optax.adam(args.lr)
    holder = {"params": params, "opt_state": tx.init(params)}

    tier = default_tier()  # C++ plane when native/libtpuft.so loads
    manager = Manager(
        # comm tier resolves separately (data_plane_tier): auto downgrades
        # to python under forced-hierarchical topologies, with a loud log
        comm=make_communicator(timeout_s=args.comm_timeout),
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=args.min_replicas,
        replica_id=f"train_ddp_{args.replica_group_id}",
        server_cls=manager_server_cls(tier),
        # manager RPCs (should_commit vote, checkpoint fetch) must detect a
        # wedged/dissolved peer on the same clock as the data plane: with
        # the 60 s default, a replica thawing from a freeze burned a full
        # minute in a doomed vote against a quorum that no longer existed
        # while its healthy peer trained to completion and exited
        timeout=args.comm_timeout,
    )
    opt = OptimizerWrapper(manager, tx)

    sampler = DistributedSampler(
        len(x),
        replica_rank=args.replica_group_id,
        num_replica_groups=args.num_replica_groups,
        shuffle=True,
    )

    loss_and_grad = jax.jit(jax.value_and_grad(model.loss))

    batches = list(batch_indices(sampler, args.batch_size))
    import time

    while manager.current_step() < args.steps:
        if args.step_time > 0:
            time.sleep(args.step_time)
        step = manager.current_step()
        idxs = batches[step % len(batches)]
        batch = (jnp.asarray(x[idxs]), jnp.asarray(y[idxs]))

        opt.start_step()  # quorum overlaps the forward pass
        loss, grads = loss_and_grad(holder["params"], batch)
        grads = ft_allreduce(manager, grads)
        committed = opt.step(holder, grads)
        logger.info(
            "step %d loss %.4f committed=%s participants=%d",
            step,
            float(loss),
            committed,
            manager.num_participants(),
        )

    # content hash of final params so separate replicas can be compared
    leaves = jax.tree_util.tree_leaves(holder["params"])
    digest = hashlib.sha256()
    for leaf in leaves:
        digest.update(np.ascontiguousarray(np.asarray(leaf, dtype=np.float32)))
    print(f"FINAL step={manager.current_step()} params_sha={digest.hexdigest()[:16]}")
    manager.shutdown()


if __name__ == "__main__":
    main()
