"""LocalSGD training example with periodic durable checkpoints.

Each replica trains locally and averages *parameters* every ``--sync-every``
steps (communication-reduced DP, the precursor to DiLoCo), saving a durable
checkpoint (model + Manager state) after each sync so the whole job can be
restored after total loss — live peer healing covers single-replica loss.

    python -m torchft_tpu.launcher --replicas 2 -- \
        python examples/train_localsgd.py --total-syncs 10 --platform cpu
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from torchft_tpu.tier import default_tier, make_communicator, manager_server_cls
from torchft_tpu.local_sgd import LocalSGD
from torchft_tpu.manager import Manager
from torchft_tpu.models.cnn import SimpleCNN
from torchft_tpu.utils.checkpoint import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
)

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("train_localsgd")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--total-syncs", type=int, default=10)
    parser.add_argument("--sync-every", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--ckpt-dir", default=None)
    parser.add_argument(
        "--replica-group-id",
        type=int,
        default=int(os.environ.get("REPLICA_GROUP_ID", 0)),
    )
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    rng = np.random.default_rng(args.replica_group_id)
    x = rng.normal(size=(1024, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=1024).astype(np.int32)

    model = SimpleCNN()
    params = model.init(jax.random.PRNGKey(0))
    tx = optax.adam(1e-3)
    holder = {"params": params, "opt_state": tx.init(params)}

    tier = default_tier()  # C++ plane when native/libtpuft.so loads
    manager = Manager(
        comm=make_communicator(timeout_s=60.0),  # data-plane tier dispatch
        load_state_dict=lambda s: holder.update(s),
        state_dict=lambda: dict(holder),
        min_replica_size=args.min_replicas,
        replica_id=f"train_localsgd_{args.replica_group_id}",
        quorum_timeout=120.0,
        server_cls=manager_server_cls(tier),
    )

    # restore from the latest durable checkpoint (job-level resume)
    if args.ckpt_dir:
        step = latest_step(args.ckpt_dir)
        if step is not None:
            state = load_checkpoint(args.ckpt_dir, step)
            holder.update(state["model"])
            manager.load_state_dict(state["torchft"])
            logger.info("restored durable checkpoint at step %d", step)

    local_sgd = LocalSGD(manager, holder, sync_every=args.sync_every)
    loss_and_grad = jax.jit(jax.value_and_grad(model.loss))

    syncs = 0
    with local_sgd:
        while syncs < args.total_syncs:
            idx = rng.integers(0, len(x), size=args.batch_size)
            batch = (jnp.asarray(x[idx]), jnp.asarray(y[idx]))
            loss, grads = loss_and_grad(holder["params"], batch)
            # optimizer state lives IN the holder so heals and durable
            # checkpoints always carry the trained moments
            updates, holder["opt_state"] = tx.update(
                grads, holder["opt_state"], holder["params"]
            )
            holder["params"] = optax.apply_updates(holder["params"], updates)
            result = local_sgd.step()
            if result is not None:
                syncs += 1
                logger.info("sync %d committed=%s loss %.4f", syncs, result, float(loss))
                # one writer per checkpoint dir: the participating rank-0
                # replica (see utils/checkpoint.py docstring)
                if args.ckpt_dir and result and manager.participating_rank() == 0:
                    save_checkpoint(
                        args.ckpt_dir,
                        manager.current_step(),
                        {"model": dict(holder), "torchft": manager.state_dict()},
                    )

    import hashlib

    digest = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(holder["params"]):
        digest.update(np.ascontiguousarray(np.asarray(leaf, dtype=np.float32)))
    print(f"FINAL syncs={syncs} params_sha={digest.hexdigest()[:16]}")
    manager.shutdown()


if __name__ == "__main__":
    main()
