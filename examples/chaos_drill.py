"""Scripted chaos scenario against a live FT fleet — the user-facing analog
of the reference's Monarch orchestration example
(``/root/reference/examples/monarch/train_distributed.py`` +
``utils/failure.py``): supervise N replica groups as real processes, inject
a typed failure mid-training, await the heal, and verify the fleet
converged to identical parameters.

    python examples/chaos_drill.py --replicas 3 --failure deadlock --steps 120

Failure classes (``torchft_tpu.chaos.Failure``): ``kill`` (SIGKILL +
supervisor restart + live heal), ``segfault`` (SIGSEGV, same recovery),
``deadlock`` (SIGSTOP freeze of every thread — heartbeats included — until
peers evict the frozen member via op timeouts; auto-thaw then rejoin).
"""

from __future__ import annotations

import argparse
import re
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO))

from torchft_tpu.chaos import ChaosController, Failure, ProcessReplica  # noqa: E402
from torchft_tpu.launcher import ReplicaSpec, ReplicaSupervisor  # noqa: E402
from torchft_tpu.lighthouse import LighthouseServer  # noqa: E402


def main() -> int:
    parser = argparse.ArgumentParser("chaos_drill")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument(
        "--failure",
        default="kill",
        choices=["kill", "segfault", "deadlock"],
    )
    parser.add_argument("--victim", type=int, default=1)
    parser.add_argument("--steps", type=int, default=120)
    parser.add_argument("--freeze-secs", type=float, default=12.0)
    parser.add_argument("--step-time", type=float, default=0.15)
    args = parser.parse_args()
    if not 0 <= args.victim < args.replicas:
        parser.error(
            f"--victim {args.victim} out of range for --replicas {args.replicas}"
        )
    if args.replicas < 2:
        parser.error("need --replicas >= 2 (the victim heals from a peer)")

    lighthouse = LighthouseServer(
        bind="127.0.0.1:0",
        min_replicas=1,
        join_timeout_ms=500,
        quorum_tick_ms=20,
    )
    logdir = Path(tempfile.mkdtemp(prefix="chaos_drill_"))
    cmd = [
        sys.executable,
        str(REPO / "examples" / "train_ddp.py"),
        "--steps", str(args.steps),
        "--platform", "cpu",
        "--comm-timeout", "5",
        "--step-time", str(args.step_time),
    ]
    logs = {i: logdir / f"rg{i}.log" for i in range(args.replicas)}
    supervisor = ReplicaSupervisor(
        [
            ReplicaSpec(replica_group_id=i, cmd=list(cmd), log_path=str(logs[i]))
            for i in range(args.replicas)
        ],
        f"127.0.0.1:{lighthouse.port}",
        restart_delay_s=0.5,
    )

    def _progress(gid: int):
        def read() -> int:
            # COMMITTED steps only (the ReplicaHandle.progress contract —
            # await_heal means "commits again", not "attempts again"), as
            # a max over the whole log: a restarted incarnation starts
            # logging from step 0 and must not read as regression
            try:
                text = logs[gid].read_text()
            except OSError:
                return 0
            commits = [
                int(n)
                for n in re.findall(r"step (\d+) loss \S+ committed=True", text)
            ]
            commits += [int(n) for n in re.findall(r"FINAL step=(\d+)", text)]
            return max(commits, default=0)

        return read

    controller = ChaosController(
        [
            ProcessReplica(f"rg{i}", supervisor, i, progress_fn=_progress(i))
            for i in range(args.replicas)
        ]
    )
    victim = controller.replicas[args.victim]

    runner = threading.Thread(target=supervisor.run, daemon=True)
    runner.start()
    rc = 1
    try:
        if not controller.await_progress(victim, beyond=5, timeout_s=180.0):
            print("fleet never got going", file=sys.stderr)
            return 1
        kw = (
            {"secs": args.freeze_secs}
            if args.failure == "deadlock"
            else {}
        )
        controller.inject(Failure(args.failure), victim=victim, **kw)
        print(f"injected {args.failure} into {victim.name}", flush=True)
        if not controller.await_heal(victim, timeout_s=300.0):
            print("victim never healed", file=sys.stderr)
            return 1
        print(f"{victim.name} healed; waiting for the fleet to finish")
        deadline = time.monotonic() + 60.0 + args.steps * (args.step_time + 0.4)
        runner.join(timeout=max(1.0, deadline - time.monotonic()))
        if runner.is_alive():
            print("fleet did not finish in time", file=sys.stderr)
            return 1
        # every replica must print the same final param hash
        hashes = {}
        for gid, path in logs.items():
            m = re.findall(r"FINAL step=(\d+) params_sha=(\w+)", path.read_text())
            if not m:
                print(f"replica {gid} never finished", file=sys.stderr)
                return 1
            hashes[gid] = m[-1][1]
        if len(set(hashes.values())) != 1:
            print(f"replicas diverged: {hashes}", file=sys.stderr)
            return 1
        print(
            f"DRILL PASSED: {args.replicas} replicas agree on "
            f"params_sha={next(iter(hashes.values()))} after {args.failure} "
            f"(events: {[(e.failure.value, e.victim) for e in controller.events]})"
        )
        rc = 0
    finally:
        supervisor.stop()
        lighthouse.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
