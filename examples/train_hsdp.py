"""Fault-tolerant HSDP training example (BASELINE config 3).

FSDP/TP over the replica group's mesh (ICI, inside compiled XLA programs) ×
fault-tolerant DDP over DCN (host-side, elastic membership).  This is the
shape of the north-star workload: Llama over a sharded mesh per replica
group, replica groups joining/leaving without recompilation.

    python -m torchft_tpu.launcher --replicas 2 -- \
        python examples/train_hsdp.py --steps 50 --platform cpu

On CPU set XLA_FLAGS=--xla_force_host_platform_device_count=8 to give each
process a virtual 8-device mesh.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp
import numpy as np
import optax

logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s: %(message)s")
logger = logging.getLogger("train_hsdp")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq", type=int, default=128)
    parser.add_argument("--fsdp", type=int, default=2)
    parser.add_argument("--tp", type=int, default=2)
    parser.add_argument("--dp", type=int, default=2)
    parser.add_argument(
        "--replica-group-id",
        type=int,
        default=int(os.environ.get("REPLICA_GROUP_ID", 0)),
    )
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument(
        "--quantize-outer",
        action="store_true",
        help="1-byte wire for the replica-dim gradient ring (int8 "
        "default, fp8 via TORCHFT_QUANT_KIND)",
    )
    parser.add_argument("--platform", default=None)
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from torchft_tpu.tier import default_tier, make_communicator, manager_server_cls
    from torchft_tpu.manager import Manager
    from torchft_tpu.models.llama import Llama, llama_debug
    from torchft_tpu.parallel.degraded import (
        plan_surviving,
        startup_surviving_devices,
    )
    from torchft_tpu.parallel.hsdp import HSDPTrainer, fsdp_shardings
    from torchft_tpu.parallel.mesh import make_mesh

    # degraded-mode / chaos: TORCHFT_CHAOS_DEVICE_LOSS hides N devices so
    # this replica comes up wounded — plan the surviving layout and
    # advertise the capacity fraction instead of dying
    devices = startup_surviving_devices(jax.devices())
    wanted = args.dp * args.fsdp * args.tp
    degraded_plan = None
    if len(devices) < wanted:
        degraded_plan = plan_surviving(
            len(devices), original_devices=wanted
        )
        logger.warning(
            "coming up degraded: %s (capacity %.3f)",
            degraded_plan.mesh_axes,
            degraded_plan.capacity,
        )
        mesh = make_mesh(devices=devices, **degraded_plan.mesh_axes)
    else:
        mesh = make_mesh(
            dp=args.dp, fsdp=args.fsdp, tp=args.tp, devices=devices
        )
    config = llama_debug()
    model = Llama(config)

    tier = default_tier()  # C++ plane when native/libtpuft.so loads
    manager = Manager(
        comm=make_communicator(timeout_s=60.0),  # data-plane tier dispatch
        load_state_dict=None,  # HSDPTrainer registers its own entry
        state_dict=None,
        min_replica_size=args.min_replicas,
        replica_id=f"train_hsdp_{args.replica_group_id}",
        server_cls=manager_server_cls(tier),
    )
    if degraded_plan is not None:
        try:
            manager.complete_relower(degraded_plan.capacity)
        except RuntimeError as e:
            # C++ sidecar: no capacity plumbing — run the reduced mesh but
            # register full-width (docs/operations.md §16 fallback matrix)
            logger.warning("cannot advertise degraded capacity: %s", e)
    trainer = HSDPTrainer(
        model,
        optax.adamw(1e-3),
        mesh,
        manager,
        key=jax.random.PRNGKey(0),
        quantize_outer=args.quantize_outer,
    )
    batch_sh = fsdp_shardings(model, mesh)[1]

    rng = np.random.default_rng(args.replica_group_id)
    while manager.current_step() < args.steps:
        tokens = rng.integers(
            0, config.vocab_size, size=(args.batch_size, args.seq)
        ).astype(np.int32)
        targets = np.roll(tokens, -1, axis=1)
        batch = tuple(
            jax.device_put(jnp.asarray(b), sh)
            for b, sh in zip((tokens, targets), batch_sh)
        )
        loss, committed = trainer.train_step(batch)
        logger.info(
            "step %d loss %.4f committed=%s participants=%d",
            manager.current_step() - (1 if committed else 0),
            loss,
            committed,
            manager.num_participants(),
        )

    import hashlib

    digest = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(trainer.holder["params"]):
        digest.update(
            np.ascontiguousarray(np.asarray(leaf, dtype=np.float32))
        )
    print(f"FINAL step={manager.current_step()} params_sha={digest.hexdigest()[:16]}")
    manager.shutdown()


if __name__ == "__main__":
    main()
