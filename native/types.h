// Control-plane message structs shared by lighthouse/manager (C++ twins of
// QuorumMember / Quorum / ManagerQuorumResult in torchft_tpu/wire.py, which
// mirror the reference's proto/torchft.proto messages).

#pragma once

#include <optional>
#include <string>
#include <vector>

#include "wire.h"

namespace tpuft {

struct QuorumMember {
  std::string replica_id;
  std::string address;
  std::string store_address;
  int64_t step = 0;
  uint64_t world_size = 1;
  bool shrink_only = false;
  int64_t commit_failures = 0;
  std::string data;

  void encode(Writer& w) const {
    w.str(replica_id);
    w.str(address);
    w.str(store_address);
    w.i64(step);
    w.u64(world_size);
    w.boolean(shrink_only);
    w.i64(commit_failures);
    w.str(data);
  }
  static QuorumMember decode(Reader& r) {
    QuorumMember m;
    m.replica_id = r.str();
    m.address = r.str();
    m.store_address = r.str();
    m.step = r.i64();
    m.world_size = r.u64();
    m.shrink_only = r.boolean();
    m.commit_failures = r.i64();
    m.data = r.str();
    return m;
  }
};

struct Quorum {
  int64_t quorum_id = 0;
  std::vector<QuorumMember> participants;
  double created = 0.0;

  void encode(Writer& w) const {
    w.i64(quorum_id);
    w.f64(created);
    w.u32(static_cast<uint32_t>(participants.size()));
    for (const auto& p : participants) p.encode(w);
  }
  static Quorum decode(Reader& r) {
    Quorum q;
    q.quorum_id = r.i64();
    q.created = r.f64();
    uint32_t n = r.u32();
    q.participants.reserve(n);
    for (uint32_t i = 0; i < n; ++i) q.participants.push_back(QuorumMember::decode(r));
    return q;
  }
};

struct ManagerQuorumResult {
  int64_t quorum_id = 0;
  int64_t replica_rank = 0;
  int64_t replica_world_size = 1;
  std::string recover_src_manager_address;
  std::optional<int64_t> recover_src_replica_rank;
  std::vector<int64_t> recover_dst_replica_ranks;
  std::string store_address;
  int64_t max_step = 0;
  std::optional<int64_t> max_replica_rank;
  int64_t max_world_size = 1;
  bool heal = false;
  int64_t commit_failures = 0;
  std::vector<std::string> replica_ids;

  void encode(Writer& w) const {
    w.i64(quorum_id);
    w.i64(replica_rank);
    w.i64(replica_world_size);
    w.str(recover_src_manager_address);
    w.opt_i64(recover_src_replica_rank);
    w.u32(static_cast<uint32_t>(recover_dst_replica_ranks.size()));
    for (int64_t r : recover_dst_replica_ranks) w.i64(r);
    w.str(store_address);
    w.i64(max_step);
    w.opt_i64(max_replica_rank);
    w.i64(max_world_size);
    w.boolean(heal);
    w.i64(commit_failures);
    w.u32(static_cast<uint32_t>(replica_ids.size()));
    for (const auto& id : replica_ids) w.str(id);
  }
};

}  // namespace tpuft
