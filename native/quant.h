// Rowwise int8 quantization kernels for the host/DCN collective tier.
//
// The reference fuses fp8 quantize/dequantize/reduce into triton kernels
// (torchft/quantization.py:44-686, CUDA).  On TPU the device twin is the
// Pallas kernel (torchft_tpu/ops/pallas_quant.py); these are the HOST
// kernels used by the DCN pipeline (torchft_tpu/collectives.py) — the
// numpy versions make several full passes over the buffer and allocate
// temporaries, which dominates the quantized allreduce at DiLoCo sizes.
// Here each row is processed in one pass (absmax, then scale+round) with
// -march=native autovectorization, parallelized over row blocks.
#pragma once

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace tpuft {
namespace quant {

// Parallel-for over [0, n) in contiguous blocks; plain threads (no pool):
// kernels run a handful of times per sync, thread spawn cost is noise next
// to the memory traffic.
template <typename F>
inline void parallel_rows(int64_t n, F&& f) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t workers = std::min<int64_t>(hw ? hw : 4, 16);
  // small inputs: not worth spawning
  if (n < workers * 8) {
    f(0, n);
    return;
  }
  int64_t per = (n + workers - 1) / workers;
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (int64_t w = 0; w < workers; ++w) {
    int64_t lo = w * per, hi = std::min(n, lo + per);
    if (lo >= hi) break;
    threads.emplace_back([lo, hi, &f] { f(lo, hi); });
  }
  for (auto& t : threads) t.join();
}

// flat float32 [n] -> q int8 [rows, row_size] (tail zero-padded), scales
// float32 [rows]; rows = ceil(n / row_size).  scale = absmax/127 per row.
inline void quantize_rowwise(const float* in, int64_t n, int64_t row_size,
                             int8_t* q, float* scales) {
  int64_t rows = std::max<int64_t>(1, (n + row_size - 1) / row_size);
  parallel_rows(rows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t start = r * row_size;
      int64_t valid = std::max<int64_t>(
          0, std::min<int64_t>(row_size, n - start));
      const float* src = in + start;
      float absmax = 0.f;
      for (int64_t i = 0; i < valid; ++i) {
        float a = std::fabs(src[i]);
        if (a > absmax) absmax = a;
      }
      float scale = absmax / 127.0f;
      scales[r] = scale;
      float inv = scale > 0.f ? 1.0f / scale : 0.f;
      int8_t* dst = q + r * row_size;
      for (int64_t i = 0; i < valid; ++i) {
        float v = src[i] * inv;
        v = v > 127.f ? 127.f : (v < -127.f ? -127.f : v);
        dst[i] = static_cast<int8_t>(std::nearbyintf(v));
      }
      if (valid < row_size)
        std::memset(dst + valid, 0, static_cast<size_t>(row_size - valid));
    }
  });
}

// q int8 [rows, row_size], scales [rows] -> out float32 [n]
inline void dequantize_rowwise(const int8_t* q, const float* scales,
                               int64_t n, int64_t row_size, float* out) {
  int64_t rows = std::max<int64_t>(1, (n + row_size - 1) / row_size);
  parallel_rows(rows, [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; ++r) {
      int64_t start = r * row_size;
      int64_t valid = std::max<int64_t>(
          0, std::min<int64_t>(row_size, n - start));
      float scale = scales[r];
      const int8_t* src = q + r * row_size;
      float* dst = out + start;
      for (int64_t i = 0; i < valid; ++i)
        dst[i] = static_cast<float>(src[i]) * scale;
    }
  });
}

// qs int8 [w, rows, row_size], scales [w, rows] -> requantized sum
// (q_out [rows, row_size], s_out [rows]).  Dequant-sum-requant per row in
// one pass with a stack accumulator row (the fused_reduce analog).
inline void reduce_rowwise(const int8_t* qs, const float* scales, int64_t w,
                           int64_t rows, int64_t row_size, int8_t* q_out,
                           float* s_out) {
  parallel_rows(rows, [&](int64_t lo, int64_t hi) {
    std::vector<float> acc(static_cast<size_t>(row_size));
    for (int64_t r = lo; r < hi; ++r) {
      float* a = acc.data();
      {
        const int8_t* src = qs + r * row_size;
        float s = scales[r];
        for (int64_t i = 0; i < row_size; ++i)
          a[i] = static_cast<float>(src[i]) * s;
      }
      for (int64_t k = 1; k < w; ++k) {
        const int8_t* src = qs + (k * rows + r) * row_size;
        float s = scales[k * rows + r];
        for (int64_t i = 0; i < row_size; ++i)
          a[i] += static_cast<float>(src[i]) * s;
      }
      float absmax = 0.f;
      for (int64_t i = 0; i < row_size; ++i) {
        float v = std::fabs(a[i]);
        if (v > absmax) absmax = v;
      }
      float scale = absmax / 127.0f;
      s_out[r] = scale;
      float inv = scale > 0.f ? 1.0f / scale : 0.f;
      int8_t* dst = q_out + r * row_size;
      for (int64_t i = 0; i < row_size; ++i) {
        float v = a[i] * inv;
        v = v > 127.f ? 127.f : (v < -127.f ? -127.f : v);
        dst[i] = static_cast<int8_t>(std::nearbyintf(v));
      }
    }
  });
}

}  // namespace quant
}  // namespace tpuft
