// C ABI for the torchft_tpu native runtime (loaded from Python via ctypes —
// the environment has no pybind11; this keeps bindings dependency-free).
//
// Error convention: functions returning int use 0 = ok, -1 = error with the
// message retrievable via tpuft_last_error() (thread-local).

#include <cstdlib>
#include <cstring>
#include <string>

#include "comm.h"
#include "lighthouse.h"
#include "manager.h"
#include "quant.h"
#include "store.h"

namespace {
thread_local std::string g_last_error;

template <typename Fn>
int guarded(Fn&& fn) {
  try {
    fn();
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return -1;
  } catch (...) {
    g_last_error = "unknown error";
    return -1;
  }
}
}  // namespace

extern "C" {

const char* tpuft_last_error() { return g_last_error.c_str(); }

// ---------------- store ----------------

void* tpuft_store_new(const char* bind_addr) {
  try {
    return new tpuft::StoreServer(bind_addr);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

int tpuft_store_port(void* h) {
  return static_cast<tpuft::StoreServer*>(h)->port();
}

void tpuft_store_free(void* h) {
  auto* server = static_cast<tpuft::StoreServer*>(h);
  server->shutdown();
  delete server;
}

// ---------------- lighthouse ----------------

void* tpuft_lighthouse_new(const char* bind_addr, uint64_t min_replicas,
                           uint64_t join_timeout_ms, uint64_t quorum_tick_ms,
                           uint64_t heartbeat_timeout_ms) {
  try {
    tpuft::LighthouseConfig cfg;
    cfg.min_replicas = min_replicas;
    cfg.join_timeout_ms = join_timeout_ms;
    cfg.quorum_tick_ms = quorum_tick_ms;
    cfg.heartbeat_timeout_ms = heartbeat_timeout_ms;
    return new tpuft::LighthouseServer(bind_addr, cfg);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

int tpuft_lighthouse_port(void* h) {
  return static_cast<tpuft::LighthouseServer*>(h)->port();
}

void tpuft_lighthouse_free(void* h) {
  auto* server = static_cast<tpuft::LighthouseServer*>(h);
  server->shutdown();
  delete server;
}

// ---------------- manager ----------------

void* tpuft_manager_new(const char* replica_id, const char* lighthouse_addr,
                        const char* hostname, const char* bind_addr,
                        const char* store_addr, uint64_t world_size,
                        double heartbeat_interval_s, double connect_timeout_s,
                        int64_t quorum_retries) {
  try {
    return new tpuft::ManagerServer(replica_id, lighthouse_addr, hostname,
                                    bind_addr, store_addr, world_size,
                                    heartbeat_interval_s, connect_timeout_s,
                                    quorum_retries);
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return nullptr;
  }
}

int tpuft_manager_port(void* h) {
  return static_cast<tpuft::ManagerServer*>(h)->port();
}

void tpuft_manager_free(void* h) {
  auto* server = static_cast<tpuft::ManagerServer*>(h);
  server->shutdown();
  delete server;
}

// ---------------- communicator ----------------

void* tpuft_comm_new(double timeout_s) {
  return new tpuft::Communicator(timeout_s);
}

int tpuft_comm_configure(void* h, const char* store_prefixed_addr,
                         int64_t rank, int64_t world_size) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->configure(store_prefixed_addr, rank, world_size); });
}

int tpuft_comm_allreduce(void* h, void* data, uint64_t nbytes, int32_t dtype,
                         int32_t op) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] {
    comm->allreduce(data, nbytes, static_cast<tpuft::DType>(dtype),
                    static_cast<tpuft::RedOp>(op));
  });
}

// zero-copy multi-buffer allreduce: `bufs`/`lens` describe n scattered
// caller buffers (all holding whole elements of `dtype`) treated as one
// logical payload — frames leave and land via sendmsg/recvmsg straight
// against these buffers, no staging concatenation on either side.
int tpuft_comm_allreduce_iov(void* h, void* const* bufs, const uint64_t* lens,
                             uint64_t n, int32_t dtype, int32_t op) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] {
    comm->allreduce_iov(bufs, lens, n, static_cast<tpuft::DType>(dtype),
                        static_cast<tpuft::RedOp>(op));
  });
}

int tpuft_comm_reduce_scatter(void* h, void* data, uint64_t nbytes,
                              int32_t dtype, int32_t op, void* out,
                              uint64_t out_cap, uint64_t* out_bytes) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] {
    *out_bytes = comm->reduce_scatter(data, nbytes,
                                      static_cast<tpuft::DType>(dtype),
                                      static_cast<tpuft::RedOp>(op), out,
                                      out_cap);
  });
}

int tpuft_comm_broadcast(void* h, void* data, uint64_t nbytes, int64_t root) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->broadcast(data, nbytes, root); });
}

int tpuft_comm_send(void* h, const void* data, uint64_t nbytes, int64_t dst,
                    uint64_t tag) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->send(data, nbytes, dst, tag); });
}

int tpuft_comm_recv_alloc(void* h, int64_t src, uint64_t tag, uint8_t** out,
                          uint64_t* out_n) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] {
    auto data = comm->recv_dynamic(src, tag);
    *out = static_cast<uint8_t*>(std::malloc(data.size()));
    std::memcpy(*out, data.data(), data.size());
    *out_n = data.size();
  });
}

void tpuft_buffer_free(void* p) { std::free(p); }

int tpuft_comm_recv_into(void* h, int64_t src, uint64_t tag, void* buf,
                         uint64_t cap, uint64_t* out_n) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { *out_n = comm->recv_into(src, tag, buf, cap); });
}

int tpuft_comm_alltoall(void* h, const void* in, void* out,
                        uint64_t chunk_bytes, uint64_t tag) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->alltoall(in, out, chunk_bytes, tag); });
}

// scatter-gather alltoall: one pointer per destination rank's chunk (the
// chunks need not be contiguous with each other)
int tpuft_comm_alltoall_ptrs(void* h, const void* const* ins, void* out,
                             uint64_t chunk_bytes, uint64_t tag) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->alltoall_ptrs(ins, out, chunk_bytes, tag); });
}

int tpuft_comm_allgather(void* h, const void* in, void* out,
                         uint64_t chunk_bytes, uint64_t tag) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->allgather(in, out, chunk_bytes, tag); });
}

// per-lane counters of the current epoch (tx/rx payload bytes, stall
// events) — the native half of the tier-agnostic lane_stats() surface.
// Returns the lane count; fills up to `cap` entries per array.
uint64_t tpuft_comm_lane_stats(void* h, uint64_t* tx, uint64_t* rx,
                               uint64_t* stalls, uint64_t cap,
                               uint64_t* stripe_floor) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  *stripe_floor = comm->stripe_floor();
  return comm->lane_stats(tx, rx, stalls, cap);
}

// consume-drain of the C-side flight-recorder ring (fixed slots recording
// the epoch lifecycle): fills up to `cap` events oldest-first and returns
// the count.  obs/flight.py merges the drained events into the Python
// replica dump (the fleet postmortem view spans both tiers).
uint64_t tpuft_comm_flight_drain(void* h, uint64_t* seqs, double* ts,
                                 uint32_t* evs, int64_t* a, int64_t* b,
                                 uint64_t cap) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return comm->flight_drain(seqs, ts, evs, a, b, cap);
}

int tpuft_comm_barrier(void* h) {
  auto* comm = static_cast<tpuft::Communicator*>(h);
  return guarded([&] { comm->barrier(); });
}

void tpuft_comm_abort(void* h) {
  static_cast<tpuft::Communicator*>(h)->abort();
}

void tpuft_comm_free(void* h) { delete static_cast<tpuft::Communicator*>(h); }

// ---------------- quantization kernels ----------------

int tpuft_quantize_rowwise(const float* in, int64_t n, int64_t row_size,
                           int8_t* q, float* scales) {
  return guarded(
      [&] { tpuft::quant::quantize_rowwise(in, n, row_size, q, scales); });
}

int tpuft_dequantize_rowwise(const int8_t* q, const float* scales, int64_t n,
                             int64_t row_size, float* out) {
  return guarded(
      [&] { tpuft::quant::dequantize_rowwise(q, scales, n, row_size, out); });
}

int tpuft_reduce_rowwise(const int8_t* qs, const float* scales, int64_t w,
                         int64_t rows, int64_t row_size, int8_t* q_out,
                         float* s_out) {
  return guarded([&] {
    tpuft::quant::reduce_rowwise(qs, scales, w, rows, row_size, q_out, s_out);
  });
}

}  // extern "C"
