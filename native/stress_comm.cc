// Concurrency stress harness for the native communicator, built to run
// under TSan and ASan/UBSan in CI (make tsan / make asan).
//
// Everything runs IN ONE PROCESS (sanitizers cannot see across fork):
// an in-process StoreServer, one Communicator per rank, one op thread per
// rank (the native contract: ops are serialized per communicator), and a
// controller thread that injects the exact overlap production hits —
// abort() fired mid-collective from a foreign thread, then configure()
// called while the superseded op thread is still unwinding (the
// torn-EpochIO-pointer class the PR 8 review caught; the atomic
// epoch-scalar members and the LanePool submit-after-stop inline path
// exist because THIS harness flagged them).
//
// Phases:
//   A  correctness churn — allreduce / reduce_scatter / alltoall /
//      allgather / broadcast / p2p, every result verified bit-exactly,
//      all ranks concurrent (exercises LanePool, the striped send/recv
//      paths, and OpLatch under real thread interleavings);
//   B  abort + epoch-swap churn — op threads hammer verified allreduces
//      nonstop while the controller repeatedly aborts every communicator
//      mid-flight and drives a full re-rendezvous; op-thread errors are
//      expected and swallowed, every SUCCESSFUL op must still verify
//      (a torn epoch that silently corrupts data fails here), and each
//      settled epoch must complete at least one verified allreduce per
//      rank.
//
// Runs at TORCHFT_RING_LANES=2 so the per-lane worker pool and the
// lane-striped framing are engaged throughout; abort mid-striped-op is the
// native tier's lane-failover story (every lane to the peer dies at once).
//
// Exit 0 on success.  Sanitizer findings fail the run via halt_on_error
// (CI sets TSAN_OPTIONS / ASAN_OPTIONS / UBSAN_OPTIONS).

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "comm.h"
#include "store.h"

#if defined(__SANITIZE_THREAD__)
#include <pthread.h>
#include <time.h>
// This toolchain's libtsan intercepts pthread_cond_timedwait but NOT
// pthread_cond_clockwait (added in glibc 2.30; libstdc++'s
// condition_variable::wait_until uses it for steady_clock deadlines).  An
// unintercepted clockwait means TSan never sees the mutex release inside
// the wait, corrupting its lock bookkeeping into flaky bogus
// "double lock of a mutex" reports at the next honest lock site
// (reproduced ~1/5 runs against store.h's STORE_GET wait).  Interpose the
// missing symbol and forward to the intercepted timedwait with the
// deadline rebased onto the condvar's clock (CLOCK_REALTIME for a
// default-initialized pthread_cond) — semantics preserved modulo realtime
// jumps during a test wait, and tsan.supp stays empty.
extern "C" int pthread_cond_clockwait(pthread_cond_t* cond,
                                      pthread_mutex_t* mu, clockid_t clock,
                                      const struct timespec* abstime) {
  struct timespec now_clock, now_real, real_abs;
  ::clock_gettime(clock, &now_clock);
  ::clock_gettime(CLOCK_REALTIME, &now_real);
  int64_t rem_ns = (abstime->tv_sec - now_clock.tv_sec) * 1000000000LL +
                   (abstime->tv_nsec - now_clock.tv_nsec);
  if (rem_ns < 0) rem_ns = 0;
  int64_t real_ns =
      now_real.tv_sec * 1000000000LL + now_real.tv_nsec + rem_ns;
  real_abs.tv_sec = real_ns / 1000000000LL;
  real_abs.tv_nsec = real_ns % 1000000000LL;
  return ::pthread_cond_timedwait(cond, mu, &real_abs);
}
#endif

using namespace tpuft;

namespace {

constexpr int kWorld = 3;
constexpr size_t kReduceFloats = 256 << 10;  // 1 MiB: engages 2 lanes
constexpr size_t kChunkBytes = 64 << 10;
constexpr int kPhaseAIters = 4;
constexpr int kPhaseBEpochs = 5;
constexpr double kOpTimeoutS = 20.0;

std::atomic<int> g_failures{0};

void fail(const std::string& msg) {
  std::fprintf(stderr, "FAIL: %s\n", msg.c_str());
  g_failures.fetch_add(1);
}

void check(bool ok, const std::string& msg) {
  if (!ok) fail(msg);
}

double expected_sum(int world) {
  double s = 0;
  for (int r = 0; r < world; ++r) s += r + 1;
  return s;
}

// ---------------------------------------------------------------------------
// Phase A: verified collective churn, stable epoch
// ---------------------------------------------------------------------------

void phase_a_rank(Communicator* comm, int rank, const std::string& store_addr) {
  comm->configure(store_addr + "/stress_a", rank, kWorld);
  std::vector<float> buf(kReduceFloats);
  std::vector<uint8_t> bytes_in(kChunkBytes * kWorld), bytes_out(kChunkBytes * kWorld);
  const float want_sum = static_cast<float>(expected_sum(kWorld));

  for (int it = 0; it < kPhaseAIters; ++it) {
    // allreduce
    std::fill(buf.begin(), buf.end(), static_cast<float>(rank + 1));
    comm->allreduce(buf.data(), buf.size() * 4, DT_F32, OP_SUM);
    for (size_t i = 0; i < buf.size(); ++i) {
      if (buf[i] != want_sum) {
        fail("phase A allreduce corrupt at " + std::to_string(i));
        break;
      }
    }

    // reduce_scatter: own chunk fully reduced
    std::fill(buf.begin(), buf.end(), static_cast<float>(rank + 1));
    std::vector<float> own(buf.size() / kWorld + kWorld);
    size_t got = comm->reduce_scatter(buf.data(), buf.size() * 4, DT_F32,
                                      OP_SUM, own.data(), own.size() * 4);
    for (size_t i = 0; i < got / 4; ++i) {
      if (own[i] != want_sum) {
        fail("phase A reduce_scatter corrupt at " + std::to_string(i));
        break;
      }
    }

    // alltoall: chunk for peer p carries byte (rank*16 + p)
    for (int p = 0; p < kWorld; ++p)
      std::memset(bytes_in.data() + p * kChunkBytes,
                  rank * 16 + p, kChunkBytes);
    comm->alltoall(bytes_in.data(), bytes_out.data(), kChunkBytes,
                   /*tag=*/7 + it);
    for (int p = 0; p < kWorld; ++p) {
      uint8_t want = static_cast<uint8_t>(p * 16 + rank);
      if (bytes_out[p * kChunkBytes] != want ||
          bytes_out[(p + 1) * kChunkBytes - 1] != want)
        fail("phase A alltoall corrupt from rank " + std::to_string(p));
    }

    // allgather
    std::memset(bytes_in.data(), 100 + rank, kChunkBytes);
    comm->allgather(bytes_in.data(), bytes_out.data(), kChunkBytes,
                    /*tag=*/3 + it);
    for (int p = 0; p < kWorld; ++p)
      if (bytes_out[p * kChunkBytes] != 100 + p)
        fail("phase A allgather corrupt from rank " + std::to_string(p));

    // broadcast (rotating root) — 1 MiB payload so it stripes
    int root = it % kWorld;
    std::fill(buf.begin(), buf.end(),
              rank == root ? static_cast<float>(42 + it) : 0.0f);
    comm->broadcast(buf.data(), buf.size() * 4, root);
    if (buf.front() != static_cast<float>(42 + it) ||
        buf.back() != static_cast<float>(42 + it))
      fail("phase A broadcast corrupt");

    // p2p ring: rank -> rank+1
    uint8_t token[64];
    std::memset(token, rank + 1, sizeof(token));
    int right = (rank + 1) % kWorld;
    int left = (rank + kWorld - 1) % kWorld;
    if (rank % 2 == 0) {
      comm->send(token, sizeof(token), right, /*tag=*/5);
      auto rx = comm->recv_dynamic(left, /*tag=*/5);
      check(rx.size() == sizeof(token) && rx[0] == uint8_t(left + 1),
            "phase A p2p corrupt (even)");
    } else {
      auto rx = comm->recv_dynamic(left, /*tag=*/5);
      comm->send(token, sizeof(token), right, /*tag=*/5);
      check(rx.size() == sizeof(token) && rx[0] == uint8_t(left + 1),
            "phase A p2p corrupt (odd)");
    }
  }
}

// ---------------------------------------------------------------------------
// Phase B: abort + epoch-swap churn against in-flight ops
// ---------------------------------------------------------------------------

struct BState {
  std::atomic<int> epoch{0};         // controller bumps after each reconfigure
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> settled{0};  // bitmask: rank r verified epoch bit
};

// Op thread: hammers verified allreduces NONSTOP, never configures.  The
// controller aborts and re-rendezvouses this communicator from foreign
// threads while we are mid-op — the exact overlap CppCommunicator
// produces (tpuft_comm_configure runs on the caller thread while the
// superseded epoch's op thread is still unwinding).  Errors are expected
// churn; any op that REPORTS success must still be bit-exact.
void phase_b_rank(Communicator* comm, int rank, BState* st) {
  std::vector<float> buf(kReduceFloats);
  const float want_sum = static_cast<float>(expected_sum(kWorld));

  while (!st->stop.load()) {
    std::fill(buf.begin(), buf.end(), static_cast<float>(rank + 1));
    int epoch_at_start = st->epoch.load();
    try {
      comm->allreduce(buf.data(), buf.size() * 4, DT_F32, OP_SUM);
    } catch (const std::exception&) {
      // aborted / superseded / mid-rendezvous: expected under churn; the
      // brief nap keeps the error path from spinning hot while the
      // controller rebuilds the epoch
      std::this_thread::sleep_for(std::chrono::microseconds(500));
      continue;
    }
    bool ok = true;
    for (size_t i = 0; i < buf.size(); ++i) {
      if (buf[i] != want_sum) {
        ok = false;
        fail("phase B silent corruption at index " + std::to_string(i));
        break;
      }
    }
    // only ops that ran wholly inside one controller epoch count toward
    // settling it (the controller zeroes the mask after publishing the
    // epoch, so a re-verify next iteration restores any cleared bit)
    if (ok && st->epoch.load() == epoch_at_start)
      st->settled.fetch_or(uint64_t(1) << rank);
  }
}

}  // namespace

int main() {
  // two lanes: the per-lane worker pool and striped framing run throughout;
  // an abort mid-striped-op kills every lane to the peer at once (the
  // native tier's lane-failure story)
  ::setenv("TORCHFT_RING_LANES", "2", 1);

  StoreServer store("127.0.0.1:0");
  std::string addr = "127.0.0.1:" + std::to_string(store.port());

  std::vector<std::unique_ptr<Communicator>> comms;
  for (int r = 0; r < kWorld; ++r)
    comms.push_back(std::make_unique<Communicator>(kOpTimeoutS));

  // --- phase A ---------------------------------------------------------
  {
    std::vector<std::thread> ranks;
    for (int r = 0; r < kWorld; ++r)
      ranks.emplace_back(phase_a_rank, comms[r].get(), r, addr);
    for (auto& t : ranks) t.join();
    std::printf("stress_comm: phase A done (%d iters x %d ranks)\n",
                kPhaseAIters, kWorld);
  }

  // --- phase B ---------------------------------------------------------
  {
    BState st;
    const uint64_t all_ranks = (uint64_t(1) << kWorld) - 1;
    std::vector<std::thread> ranks;
    for (int r = 0; r < kWorld; ++r)
      ranks.emplace_back(phase_b_rank, comms[r].get(), r, &st);

    int verified_epochs = 0;
    for (int e = 1; e <= kPhaseBEpochs; ++e) {
      // yank the epoch out from under the op threads: abort mid-op from
      // this foreign thread, then re-rendezvous every communicator from
      // fresh controller threads WHILE the superseded ops unwind — the
      // torn-EpochIO overlap, continuously
      for (auto& c : comms) c->abort();
      std::vector<std::thread> cfg;
      for (int r = 0; r < kWorld; ++r)
        cfg.emplace_back([&, r] {
          try {
            comms[r]->configure(addr + "/stress_b_" + std::to_string(e), r,
                                kWorld);
          } catch (const std::exception& ex) {
            fail("phase B configure rank " + std::to_string(r) + " epoch " +
                 std::to_string(e) + ": " + ex.what());
          }
        });
      for (auto& t : cfg) t.join();
      st.epoch.store(e);
      st.settled.store(0);  // after the publish: stale-epoch bits can't leak in
      // wait (bounded) for every rank to land one VERIFIED allreduce on
      // this epoch before tearing it down again
      auto deadline =
          std::chrono::steady_clock::now() + std::chrono::seconds(60);
      while (st.settled.load() != all_ranks &&
             std::chrono::steady_clock::now() < deadline)
        std::this_thread::sleep_for(std::chrono::milliseconds(2));
      if (st.settled.load() == all_ranks)
        ++verified_epochs;
      else
        fail("phase B epoch " + std::to_string(e) + " never settled");
      // a short overlap window with ops back in flight before the next yank
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    st.stop.store(true);
    for (auto& c : comms) c->abort();  // unblock any parked op
    for (auto& t : ranks) t.join();
    std::printf("stress_comm: phase B done (%d/%d epochs verified)\n",
                verified_epochs, kPhaseBEpochs);
    check(verified_epochs == kPhaseBEpochs, "phase B epochs missed");
  }

  comms.clear();
  if (g_failures.load() != 0) {
    std::fprintf(stderr, "stress_comm: %d failure(s)\n", g_failures.load());
    return 1;
  }
  std::printf("stress_comm: OK\n");
  return 0;
}
