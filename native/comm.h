// Host-side data-plane communicator — C++ twin of the Python
// TCPCommunicator mesh tier (torchft_tpu/communicator.py), built for DCN
// throughput: poll()-driven duplex IO on non-blocking sockets, large socket
// buffers, -O3 vectorized reduction loops, ring allreduce
// (reduce-scatter + allgather), alltoall/allgather, broadcast, send/recv.
//
// All ops are synchronous at this level and abortable: abort() flips a flag
// and shuts the sockets down, unblocking any op mid-IO (the userspace
// timeout/abort doctrine, SURVEY.md §5.8.5).  The Python wrapper
// (torchft_tpu/native.py CppCommunicator) serializes ops on an op thread
// and layers Work/timeout semantics on top.

#pragma once

#include <fcntl.h>
#include <sys/uio.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstring>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include "store.h"
#include "wire.h"

namespace tpuft {

enum DType : int32_t {
  DT_F32 = 0,
  DT_F64 = 1,
  DT_I32 = 2,
  DT_I64 = 3,
  DT_BF16 = 4,
  DT_U8 = 5,
  DT_I8 = 6,
};

enum RedOp : int32_t { OP_SUM = 0, OP_MAX = 1, OP_MIN = 2 };

inline size_t dtype_size(DType dt) {
  switch (dt) {
    case DT_F64:
    case DT_I64:
      return 8;
    case DT_F32:
    case DT_I32:
      return 4;
    case DT_BF16:
      return 2;
    default:
      return 1;
  }
}

inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

template <typename T>
inline void reduce_typed(T* acc, const T* in, size_t n, RedOp op) {
  switch (op) {
    case OP_SUM:
      for (size_t i = 0; i < n; ++i) acc[i] += in[i];
      break;
    case OP_MAX:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] > in[i] ? acc[i] : in[i];
      break;
    case OP_MIN:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] < in[i] ? acc[i] : in[i];
      break;
  }
}

inline void reduce_buffer(void* acc, const void* in, size_t nbytes, DType dt,
                          RedOp op) {
  switch (dt) {
    case DT_F32:
      reduce_typed(static_cast<float*>(acc), static_cast<const float*>(in),
                   nbytes / 4, op);
      break;
    case DT_F64:
      reduce_typed(static_cast<double*>(acc), static_cast<const double*>(in),
                   nbytes / 8, op);
      break;
    case DT_I32:
      reduce_typed(static_cast<int32_t*>(acc), static_cast<const int32_t*>(in),
                   nbytes / 4, op);
      break;
    case DT_I64:
      reduce_typed(static_cast<int64_t*>(acc), static_cast<const int64_t*>(in),
                   nbytes / 8, op);
      break;
    case DT_I8:
      reduce_typed(static_cast<int8_t*>(acc), static_cast<const int8_t*>(in),
                   nbytes, op);
      break;
    case DT_U8:
      reduce_typed(static_cast<uint8_t*>(acc), static_cast<const uint8_t*>(in),
                   nbytes, op);
      break;
    case DT_BF16: {
      auto* a = static_cast<uint16_t*>(acc);
      auto* b = static_cast<const uint16_t*>(in);
      size_t n = nbytes / 2;
      for (size_t i = 0; i < n; ++i) {
        float fa = bf16_to_f32(a[i]);
        float fb = bf16_to_f32(b[i]);
        float out = op == OP_SUM   ? fa + fb
                    : op == OP_MAX ? (fa > fb ? fa : fb)
                                   : (fa < fb ? fa : fb);
        a[i] = f32_to_bf16(out);
      }
      break;
    }
  }
}

struct CommError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Parallel-connection ("lane") config for striped collectives — must agree
// with the Python tier (torchft_tpu/communicator.py _ring_lanes /
// _stripe_floor) and be uniform across ranks (verified in the rendezvous
// hello).  The native tier has no network emulator, so "auto" resolves to 1
// here; set an explicit integer in mixed-tier deployments.
inline size_t ring_lanes_from_env() {
  const char* v = std::getenv("TORCHFT_RING_LANES");
  if (!v || !*v || std::string(v) == "auto") return 1;
  long n = std::strtol(v, nullptr, 10);
  return n >= 1 ? static_cast<size_t>(n) : 1;
}

inline size_t stripe_floor_from_env() {
  const char* v = std::getenv("TORCHFT_RING_FRAME_KB");
  if (!v || !*v || std::string(v) == "auto") return size_t(64) << 10;
  double kb = std::strtod(v, nullptr);
  size_t b = static_cast<size_t>(kb * 1024);
  return b < 64 ? 64 : b;
}

// --- hierarchical topology (leader ring) ------------------------------------
//
// Mirror of the Python tier's host grouping (communicator.py _HostTopology)
// so the tiers agree on the hierarchical WIRE SCHEDULE: hosts are ordered
// by their SMALLEST global rank, each host's leader IS that rank, and
// cross-host collectives run over the leader ring in that order (ring
// position replaces rank in the chunk schedule — see the `ring` parameter
// of ring_reduce_phase / ring_allgather_phase).  The shared-memory
// intra-host hop is host-local and never crosses tiers.  NOTE: this tier's
// configure() does not yet publish `topo_{rank}` keys, so a native rank in
// a group makes the Python ranks' "auto" fall back to the flat ring (and a
// forced TORCHFT_HIERARCHICAL=1 fail loudly); these helpers pin the math a
// full native topology integration must reproduce byte-for-byte.

// TORCHFT_HIERARCHICAL: "auto" (default) | "0" | "1" — must be uniform
// across replicas, like TORCHFT_RING_LANES.
inline std::string hierarchical_mode_from_env() {
  const char* v = std::getenv("TORCHFT_HIERARCHICAL");
  std::string s = v ? v : "auto";
  if (s.empty() || s == "auto") return "auto";
  if (s == "1" || s == "true" || s == "on") return "1";
  if (s == "0" || s == "false" || s == "off") return "0";
  throw CommError("unparseable TORCHFT_HIERARCHICAL=" + s + " (auto|0|1)");
}

// TORCHFT_HOST_ID overrides the host identity (default: the advertised
// rendezvous address' host part — same-IP grouping).
inline std::string host_id_from_env(const std::string& fallback) {
  const char* v = std::getenv("TORCHFT_HOST_ID");
  return (v && *v) ? std::string(v) : fallback;
}

struct HostTopology {
  std::vector<std::vector<int64_t>> hosts;  // ordered by min global rank
  std::vector<int64_t> leader_ring;         // hosts[i][0] for each host

  // identical grouping math to the Python tier: ranks ascend within a
  // host, hosts order by their first (smallest) rank
  static HostTopology build(const std::map<int64_t, std::string>& host_of) {
    std::map<std::string, std::vector<int64_t>> groups;
    for (const auto& kv : host_of) groups[kv.second].push_back(kv.first);
    HostTopology t;
    for (const auto& kv : groups) t.hosts.push_back(kv.second);
    std::sort(t.hosts.begin(), t.hosts.end(),
              [](const std::vector<int64_t>& a, const std::vector<int64_t>& b) {
                return a.front() < b.front();
              });
    for (const auto& g : t.hosts) t.leader_ring.push_back(g.front());
    return t;
  }

  // the "auto" criterion, mirrored: >= 2 hosts AND a multi-member host
  bool worth_it() const {
    if (hosts.size() < 2) return false;
    for (const auto& g : hosts)
      if (g.size() > 1) return true;
    return false;
  }
};

// High bit of the hello's rank field marks the extended (multi-lane) hello:
// (rank|flag, lane, lane count, stripe floor).  Must match the Python
// tier's _LANE_HELLO_FLAG.
constexpr uint64_t kLaneHelloFlag = uint64_t(1) << 63;

class Communicator {
 public:
  explicit Communicator(double timeout_s) : timeout_s_(timeout_s) {}

  ~Communicator() {
    abort();
    close_peers();
  }

  // Rendezvous over the store: publish our listener under "{prefix}/{rank}";
  // for each pair (i, j) with i < j, j dials i — once per LANE.  Lanes are
  // parallel TCP connections one logical collective stripes frames across
  // (lane_parts); the Python tier (_TcpMesh) speaks the identical protocol:
  // legacy 8-byte hello (rank) at 1 lane, 24-byte hello (rank, lane, lane
  // count) otherwise, lane count verified loudly.  store_prefixed_addr is
  // "host:port/prefix/..." exactly like the Python tier.
  void configure(const std::string& store_prefixed_addr, int64_t rank,
                 int64_t world_size) {
    abort();  // supersede any previous epoch
    {
      // old fds go to the graveyard (closed at destruction): an op thread
      // may still reference them, and closing now could recycle fd numbers
      std::lock_guard<std::mutex> lock(state_mu_);
      for (auto& [peer, fds] : peers_)
        for (int fd : fds) graveyard_.push_back(fd);
      peers_.clear();
    }
    aborted_ = false;
    rank_ = rank;
    world_size_ = world_size;
    lanes_ = ring_lanes_from_env();
    stripe_floor_ = stripe_floor_from_env();
    if (world_size <= 1) return;

    auto slash = store_prefixed_addr.find('/');
    std::string store_addr = store_prefixed_addr.substr(0, slash);
    std::string prefix = slash == std::string::npos
                             ? std::string("root")
                             : store_prefixed_addr.substr(slash + 1);

    StoreClient store(store_addr, timeout_s_);

    int port = 0;
    int listen_fd = listen_on("0.0.0.0:0", &port);
    char host[256];
    ::gethostname(host, sizeof(host));
    std::string host_str(host);
    {
      // prefer a dialable address even on hosts with odd hostname setup
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      if (::getaddrinfo(host_str.c_str(), nullptr, &hints, &res) != 0 || !res)
        host_str = "127.0.0.1";
      if (res) ::freeaddrinfo(res);
    }
    store.set(prefix + "/" + std::to_string(rank_),
              host_str + ":" + std::to_string(port));

    // accept from higher ranks on a helper thread while dialing lower ranks
    int expected_inbound =
        static_cast<int>((world_size - rank - 1) * lanes_);
    std::map<int64_t, std::vector<int>> inbound;
    std::string accept_err;
    // bound the whole accept phase: a dead higher-rank peer must not wedge
    // configure() (the Python twin sets listener.settimeout(timeout_s))
    set_recv_timeout(listen_fd, timeout_s_);
    std::thread acceptor([&] {
      try {
        for (int i = 0; i < expected_inbound; ++i) {
          int conn = ::accept(listen_fd, nullptr, nullptr);
          if (conn < 0)
            throw CommError("rendezvous accept timed out or failed");
          configure_socket(conn);
          set_recv_timeout(conn, timeout_s_);
          uint64_t first;
          recv_exact(conn, &first, 8);
          if (!(first & kLaneHelloFlag)) {
            // legacy 8-byte hello: a single-lane peer.  A lane mismatch is
            // a config error — fail LOUDLY instead of desynchronizing.
            if (lanes_ != 1)
              throw CommError(
                  "lane-count mismatch: rank " + std::to_string(first) +
                  " has 1 lane, we have " + std::to_string(lanes_) +
                  " (TORCHFT_RING_LANES must be uniform)");
            auto& fds = inbound[static_cast<int64_t>(first)];
            fds.assign(1, conn);
          } else {
            uint64_t tail[3];  // lane, lane count, stripe floor
            recv_exact(conn, tail, 24);
            uint64_t peer_rank = first & ~kLaneHelloFlag;
            if (tail[1] != lanes_)
              throw CommError(
                  "lane-count mismatch: rank " + std::to_string(peer_rank) +
                  " has " + std::to_string(tail[1]) + " lanes, we have " +
                  std::to_string(lanes_) +
                  " (TORCHFT_RING_LANES must be uniform)");
            if (tail[2] != stripe_floor_)
              throw CommError(
                  "stripe-floor mismatch: rank " + std::to_string(peer_rank) +
                  " has " + std::to_string(tail[2]) + " bytes, we have " +
                  std::to_string(stripe_floor_) +
                  " (TORCHFT_RING_FRAME_KB must be uniform)");
            auto& fds = inbound[static_cast<int64_t>(peer_rank)];
            if (fds.size() < lanes_) fds.resize(lanes_, -1);
            fds[tail[0]] = conn;
          }
        }
      } catch (const std::exception& e) {
        accept_err = e.what();
      }
    });

    std::map<int64_t, std::vector<int>> fresh;
    try {
      for (int64_t peer = 0; peer < rank_; ++peer) {
        std::string addr =
            store.get(prefix + "/" + std::to_string(peer), timeout_s_);
        auto& fds = fresh[peer];
        for (size_t lane = 0; lane < lanes_; ++lane) {
          int fd = dial(addr, timeout_s_);
          if (lanes_ == 1) {
            uint64_t my_rank = static_cast<uint64_t>(rank_);
            send_all(fd, &my_rank, 8);
          } else {
            uint64_t hello[4] = {static_cast<uint64_t>(rank_) | kLaneHelloFlag,
                                 lane, lanes_, stripe_floor_};
            send_all(fd, hello, 32);
          }
          fds.push_back(fd);
        }
      }
      acceptor.join();
      if (!accept_err.empty())
        throw CommError("rendezvous accept failed: " + accept_err);
      for (auto& [peer, fds] : inbound) fresh[peer] = fds;
    } catch (...) {
      if (acceptor.joinable()) acceptor.join();
      for (auto& [peer, fds] : fresh)
        for (int fd : fds) ::close(fd);
      ::close(listen_fd);
      throw;
    }
    ::close(listen_fd);

    for (auto& [peer, fds] : fresh) {
      for (int fd : fds) {
        // NB: no explicit SO_SNDBUF/SO_RCVBUF — setting them disables the
        // kernel's TCP buffer autotuning, which reaches larger effective
        // windows than the rmem/wmem_max caps allow explicitly
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // blocking IO with a short timeout quantum: throughput of plain
        // send/recv, abort/deadline checks every quantum on EAGAIN
        timeval tv{0, 200000};  // 200ms
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      }
    }
    {
      std::lock_guard<std::mutex> lock(state_mu_);
      peers_ = std::move(fresh);
    }
  }

  void abort() {
    // Shut sockets down (don't close): an op thread may be mid-poll on these
    // fds; shutdown unblocks its IO with errors while keeping fd numbers
    // valid.  close happens at destruction.
    aborted_ = true;
    std::lock_guard<std::mutex> lock(state_mu_);
    for (auto& [peer, fds] : peers_)
      for (int fd : fds) ::shutdown(fd, SHUT_RDWR);
  }

  void close_peers() {
    std::lock_guard<std::mutex> lock(state_mu_);
    for (auto& [peer, fds] : peers_)
      for (int fd : fds) ::close(fd);
    peers_.clear();
    for (int fd : graveyard_) ::close(fd);
    graveyard_.clear();
  }

  std::map<int64_t, std::vector<int>> peers_snapshot() const {
    std::lock_guard<std::mutex> lock(state_mu_);
    return peers_;
  }

  // deterministic per-lane split of one frame; identical math to the Python
  // tier (_lane_parts): both endpoints derive the split from the frame
  // length alone, 64-byte aligned so no element ever straddles lanes
  std::vector<std::pair<size_t, size_t>> lane_parts(size_t nbytes) const {
    if (lanes_ <= 1 || nbytes < 2 * stripe_floor_) return {{0, nbytes}};
    size_t k = std::min(lanes_, std::max<size_t>(1, nbytes / stripe_floor_));
    if (k <= 1) return {{0, nbytes}};
    std::vector<size_t> bounds{0};
    for (size_t i = 1; i < k; ++i) {
      size_t cut = (i * nbytes / k) / 64 * 64;
      bounds.push_back(std::max(cut, bounds.back()));
    }
    bounds.push_back(nbytes);
    std::vector<std::pair<size_t, size_t>> parts;
    for (size_t i = 0; i < k; ++i) parts.emplace_back(bounds[i], bounds[i + 1]);
    return parts;
  }

  // deterministic per-replica shard split for the sharded outer optimizer;
  // identical math to the Python tier (communicator.outer_shard_parts): the
  // buffer is padded to a multiple of parts*unit and every shard is exactly
  // padded/parts bytes, so both tiers agree on shard ownership from the
  // payload size and participant count alone.  `unit` must be a positive
  // multiple of 64 (64 for raw f32 shards, the quantization row byte size
  // for int8 shards, so a boundary never splits a row).
  static std::vector<std::pair<size_t, size_t>> outer_shard_parts(
      size_t nbytes, size_t parts, size_t unit = 64) {
    if (parts < 1 || unit < 1 || unit % 64 != 0)
      throw std::invalid_argument("outer_shard_parts: bad parts/unit");
    size_t share = (nbytes + parts * unit - 1) / (parts * unit) * unit;
    std::vector<std::pair<size_t, size_t>> out;
    out.reserve(parts);
    for (size_t p = 0; p < parts; ++p)
      out.emplace_back(p * share, (p + 1) * share);
    return out;
  }

  int64_t rank() const { return rank_; }
  int64_t size() const { return world_size_; }
  void set_timeout(double t) { timeout_s_ = t; }

  // -- collectives (synchronous; caller provides an op thread) -------------

  // In-place ring allreduce over a contiguous buffer.
  void allreduce(void* data, size_t nbytes, DType dt, RedOp op) {
    allreduce_ring(data, nbytes, dt, op, full_ring());
  }

  // Ring allreduce over a RANK SUBSET (global ranks in ring order) — the
  // hierarchical leader ring.  Ring position replaces rank in the chunk
  // schedule; the full ring compiles to the identical legacy schedule
  // (position == rank), and the Python tier's `ring=` parameter speaks the
  // same frames, so mixed-tier leader rings interoperate.
  void allreduce_ring(void* data, size_t nbytes, DType dt, RedOp op,
                      const std::vector<int64_t>& ring) {
    if (ring.size() <= 1) return;
    size_t esz = dtype_size(dt);
    auto deadline = deadline_in(timeout_s_);
    auto bounds = ring_bounds(nbytes / esz, ring.size());
    uint8_t* bytes = static_cast<uint8_t*>(data);

    // reduce-scatter phase with shift 0: position ends owning chunk pos+1
    ring_reduce_phase(bytes, bounds, esz, dt, op, /*shift=*/0, deadline, ring);
    // allgather phase with matching shift: first step sends the owned chunk
    ring_allgather_phase(bytes, bounds, esz, /*shift=*/0, deadline, ring);
  }

  // reduce-scatter: `data` is reduced in place ring-wise; this rank's chunk
  // (chunk `rank` of ws near-equal chunks over the flattened elements) ends
  // up fully reduced and is copied into `out`.  Returns the chunk's bytes.
  size_t reduce_scatter(void* data, size_t nbytes, DType dt, RedOp op,
                        void* out, size_t out_cap) {
    size_t esz = dtype_size(dt);
    auto bounds = ring_bounds(nbytes / esz);
    uint8_t* bytes = static_cast<uint8_t*>(data);
    size_t own_off = bounds[rank_] * esz;
    size_t own_bytes = (bounds[rank_ + 1] - bounds[rank_]) * esz;
    if (own_bytes > out_cap)
      throw CommError("reduce_scatter out buffer too small");
    if (world_size_ > 1) {
      auto deadline = deadline_in(timeout_s_);
      // shift -1: rank ends owning chunk `rank` (conventional contract)
      ring_reduce_phase(bytes, bounds, esz, dt, op, /*shift=*/-1, deadline,
                        full_ring());
    }
    std::memcpy(out, bytes + own_off, own_bytes);
    return own_bytes;
  }

  void broadcast(void* data, size_t nbytes, int64_t root) {
    if (world_size_ <= 1) return;
    auto deadline = deadline_in(timeout_s_);
    if (rank_ == root) {
      // concurrent fan-out to every peer (send-only multi_exchange)
      const uint8_t* src = static_cast<const uint8_t*>(data);
      multi_exchange(
          peers_snapshot(),
          [&](int64_t) { return std::make_pair(src, nbytes); },
          [&](int64_t) {
            return std::make_pair(static_cast<uint8_t*>(nullptr), size_t(0));
          },
          3000, deadline);
    } else {
      recv_striped(peer_fds(root), root, 3000, data, nbytes, deadline);
    }
  }

  void send(const void* data, size_t nbytes, int64_t dst, uint64_t tag) {
    auto deadline = deadline_in(timeout_s_);
    send_framed(p2p_fd(dst), dst, tag, data, nbytes, deadline);
  }

  // zero-copy: receive one frame directly into a caller buffer; returns
  // the payload size (must be <= cap)
  size_t recv_into(int64_t src, uint64_t tag, void* buf, size_t cap) {
    auto deadline = deadline_in(timeout_s_);
    int fd = p2p_fd(src);
    uint64_t hdr[2];
    recv_loop(fd, src, hdr, 16, deadline);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(src));
    if (hdr[0] > cap) {
      // drain the payload so the stream stays frame-aligned, THEN fail
      std::vector<uint8_t> scratch(1 << 20);
      uint64_t remaining = hdr[0];
      while (remaining > 0) {
        size_t take = std::min<uint64_t>(remaining, scratch.size());
        recv_loop(fd, src, scratch.data(), take, deadline);
        remaining -= take;
      }
      throw CommError("recv_into buffer too small: payload " +
                      std::to_string(hdr[0]) + " > cap " + std::to_string(cap));
    }
    recv_loop(fd, src, buf, hdr[0], deadline);
    return hdr[0];
  }

  // receiver learns the size from the frame header
  std::vector<uint8_t> recv_dynamic(int64_t src, uint64_t tag) {
    auto deadline = deadline_in(timeout_s_);
    int fd = p2p_fd(src);
    uint64_t hdr[2];
    recv_loop(fd, src, hdr, 16, deadline);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(src));
    std::vector<uint8_t> out(hdr[0]);
    recv_loop(fd, src, out.data(), out.size(), deadline);
    return out;
  }

  // symmetric alltoall of equal-size chunks; chunks laid out contiguously in
  // `data` (ws chunks of chunk_bytes); received into `out` by source rank.
  void alltoall(const void* data, void* out, size_t chunk_bytes, uint64_t tag) {
    const uint8_t* in = static_cast<const uint8_t*>(data);
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + rank_ * chunk_bytes, in + rank_ * chunk_bytes, chunk_bytes);
    auto deadline = deadline_in(timeout_s_);
    // pairwise exchange with every peer concurrently
    multi_exchange(
        peers_snapshot(),
        [&](int64_t p) { return std::make_pair(in + p * chunk_bytes, chunk_bytes); },
        [&](int64_t p) { return std::make_pair(o + p * chunk_bytes, chunk_bytes); },
        4000 + tag, deadline);
  }

  void allgather(const void* data, void* out, size_t chunk_bytes, uint64_t tag) {
    const uint8_t* in = static_cast<const uint8_t*>(data);
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + rank_ * chunk_bytes, in, chunk_bytes);
    auto deadline = deadline_in(timeout_s_);
    multi_exchange(
        peers_snapshot(),
        [&](int64_t) { return std::make_pair(in, chunk_bytes); },
        [&](int64_t p) { return std::make_pair(o + p * chunk_bytes, chunk_bytes); },
        5000 + tag, deadline);
  }

  void barrier() {
    float token = 0.0f;
    allreduce(&token, sizeof(token), DT_F32, OP_SUM);
  }

 private:
  using TimePoint = std::chrono::steady_clock::time_point;
  static TimePoint now() { return std::chrono::steady_clock::now(); }
  TimePoint deadline_in(double seconds) const {
    return now() + std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                       std::chrono::duration<double>(seconds));
  }

  std::vector<int> peer_fds(int64_t peer) {
    std::lock_guard<std::mutex> lock(state_mu_);
    auto it = peers_.find(peer);
    if (it == peers_.end())
      throw CommError("no peer " + std::to_string(peer) +
                      (aborted_ ? " (communicator aborted)" : ""));
    return it->second;
  }

  int peer_fd(int64_t peer, size_t lane = 0) {
    std::lock_guard<std::mutex> lock(state_mu_);
    auto it = peers_.find(peer);
    if (it == peers_.end() || lane >= it->second.size())
      throw CommError("no peer " + std::to_string(peer) +
                      (aborted_ ? " (communicator aborted)" : ""));
    return it->second[lane];
  }

  // point-to-point ops ride the LAST lane whole (the only lane at lanes==1,
  // wire-identical to the pre-lane build) — heal traffic off lane 0, where
  // collective control frames concentrate; matches _TcpMesh.p2p_sock
  int p2p_fd(int64_t peer) { return peer_fd(peer, lanes_ - 1); }

  void check_abort() const {
    if (aborted_) throw CommError("communicator aborted");
  }

  // --- blocking framed IO with abort/deadline checks per quantum ---------

  void send_framed(int fd, int64_t peer, uint64_t tag, const void* buf,
                   size_t nbytes, TimePoint deadline) {
    uint64_t hdr[2] = {nbytes, tag};
    // writev: header + first payload bytes leave in ONE syscall/segment
    // (with TCP_NODELAY a separate 16-byte header send costs a segment and
    // a wakeup per frame)
    struct iovec iov[2];
    iov[0].iov_base = hdr;
    iov[0].iov_len = 16;
    iov[1].iov_base = const_cast<void*>(buf);
    iov[1].iov_len = nbytes;
    while (true) {
      check_abort();
      if (now() > deadline) throw CommError("send timed out");
      ssize_t sent = ::writev(fd, iov, 2);
      if (sent < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;
        throw CommError("send failed to rank " + std::to_string(peer));
      }
      size_t s = static_cast<size_t>(sent);
      if (s >= iov[0].iov_len + iov[1].iov_len) return;
      if (s >= iov[0].iov_len) {
        // header fully out: finish the payload with the plain loop
        size_t payload_sent = s - iov[0].iov_len;
        send_loop(fd, peer, static_cast<const uint8_t*>(buf) + payload_sent,
                  nbytes - payload_sent, deadline);
        return;
      }
      // partial header (rare): finish header then payload
      send_loop(fd, peer, reinterpret_cast<uint8_t*>(hdr) + s, 16 - s,
                deadline);
      send_loop(fd, peer, buf, nbytes, deadline);
      return;
    }
  }

  // --- lane-striped framed IO ---------------------------------------------
  //
  // One logical frame split across the lane connections by lane_parts();
  // part 0 runs on the calling thread, the rest on short-lived lane
  // threads, so on cwnd-limited links the streams genuinely run in
  // parallel.  Sub-frame boundaries are 64-byte aligned, so the reduce
  // variant can fold each lane's range independently — every element still
  // sees exactly one reduction per step: results are bit-identical to a
  // single lane.

  template <typename PartFn>
  void run_lane_parts(const std::vector<std::pair<size_t, size_t>>& parts,
                      PartFn fn) {
    if (parts.size() == 1) {
      fn(0, parts[0].first, parts[0].second);
      return;
    }
    std::mutex err_mu;
    std::string first_err;
    std::vector<std::thread> threads;
    for (size_t i = 1; i < parts.size(); ++i) {
      threads.emplace_back([&, i] {
        try {
          fn(i, parts[i].first, parts[i].second);
        } catch (const std::exception& e) {
          std::lock_guard<std::mutex> lock(err_mu);
          if (first_err.empty()) first_err = e.what();
        }
      });
    }
    try {
      fn(0, parts[0].first, parts[0].second);
    } catch (const std::exception& e) {
      std::lock_guard<std::mutex> lock(err_mu);
      if (first_err.empty()) first_err = e.what();
    }
    for (auto& t : threads) t.join();
    if (!first_err.empty()) throw CommError(first_err);
  }

  void send_striped(const std::vector<int>& fds, int64_t peer, uint64_t tag,
                    const void* buf, size_t nbytes, TimePoint deadline) {
    const uint8_t* base = static_cast<const uint8_t*>(buf);
    run_lane_parts(lane_parts(nbytes), [&](size_t lane, size_t s, size_t e) {
      send_framed(fds[lane], peer, tag, base + s, e - s, deadline);
    });
  }

  void recv_striped(const std::vector<int>& fds, int64_t peer, uint64_t tag,
                    void* buf, size_t nbytes, TimePoint deadline) {
    uint8_t* base = static_cast<uint8_t*>(buf);
    run_lane_parts(lane_parts(nbytes), [&](size_t lane, size_t s, size_t e) {
      recv_framed(fds[lane], peer, tag, base + s, e - s, deadline);
    });
  }

  void recv_striped_reduce(const std::vector<int>& fds, int64_t peer,
                           uint64_t tag, void* dst, size_t nbytes, DType dt,
                           RedOp op, TimePoint deadline,
                           std::vector<std::vector<uint8_t>>& scratches) {
    uint8_t* base = static_cast<uint8_t*>(dst);
    auto parts = lane_parts(nbytes);
    // per-lane scratch from the caller's pool (grown once, reused across
    // ring steps): the quantum-pipelined reduce runs concurrently on every
    // lane over disjoint destination ranges
    if (scratches.size() < parts.size()) scratches.resize(parts.size());
    for (size_t i = 0; i < parts.size(); ++i) {
      size_t want =
          std::min<size_t>(parts[i].second - parts[i].first, size_t(4) << 20) +
          64;
      if (scratches[i].size() < want) scratches[i].resize(want);
    }
    run_lane_parts(parts, [&](size_t lane, size_t s, size_t e) {
      recv_framed_reduce(fds[lane], peer, tag, base + s, e - s,
                         scratches[lane].data(), dt, op, deadline);
    });
  }

  // element bounds per ring chunk (first n%ws chunks one element longer)
  std::vector<size_t> ring_bounds(size_t n) const {
    return ring_bounds(n, static_cast<size_t>(world_size_));
  }

  static std::vector<size_t> ring_bounds(size_t n, size_t ws) {
    std::vector<size_t> bounds(ws + 1, 0);
    size_t base = n / ws, extra = n % ws;
    for (size_t i = 0; i < ws; ++i)
      bounds[i + 1] = bounds[i] + base + (i < extra ? 1 : 0);
    return bounds;
  }

  std::vector<int64_t> full_ring() const {
    std::vector<int64_t> ring(world_size_);
    for (int64_t i = 0; i < world_size_; ++i) ring[i] = i;
    return ring;
  }

  static int64_t ring_pos(const std::vector<int64_t>& ring, int64_t rank) {
    auto it = std::find(ring.begin(), ring.end(), rank);
    if (it == ring.end())
      throw CommError("rank " + std::to_string(rank) + " not in ring");
    return it - ring.begin();
  }

  // ring reduce phase: ws-1 duplex steps over `ring` (global ranks in ring
  // order; ws = ring.size()); with shift s, this rank's ring POSITION ends
  // up owning the fully-reduced chunk (pos + 1 + s) mod ws.  The (memory-
  // bound) reduction rides under the wire via quantum-pipelined recv.
  void ring_reduce_phase(uint8_t* bytes, const std::vector<size_t>& bounds,
                         size_t esz, DType dt, RedOp op, int64_t shift,
                         TimePoint deadline,
                         const std::vector<int64_t>& ring) {
    int64_t ws = static_cast<int64_t>(ring.size());
    int64_t pos = ring_pos(ring, rank_);
    int64_t right = ring[(pos + 1) % ws];
    int64_t left = ring[(pos - 1 + ws) % ws];
    auto chunk_ptr = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return bytes + bounds[i] * esz;
    };
    auto chunk_bytes = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return (bounds[i + 1] - bounds[i]) * esz;
    };
    std::vector<int> right_fds = peer_fds(right);
    std::vector<int> left_fds = peer_fds(left);
    std::vector<std::vector<uint8_t>> scratches;  // grown once, reused/step
    for (int64_t step = 0; step < ws - 1; ++step) {
      int64_t send_idx = pos - step + shift;
      int64_t recv_idx = pos - step - 1 + shift;
      std::string send_err;
      std::thread sender([&] {
        try {
          send_striped(right_fds, right, 1000 + step, chunk_ptr(send_idx),
                       chunk_bytes(send_idx), deadline);
        } catch (const std::exception& e) {
          send_err = e.what();
        }
      });
      try {
        recv_striped_reduce(left_fds, left, 1000 + step, chunk_ptr(recv_idx),
                            chunk_bytes(recv_idx), dt, op, deadline,
                            scratches);
      } catch (...) {
        sender.join();
        throw;
      }
      sender.join();
      if (!send_err.empty()) throw CommError(send_err);
    }
  }

  // ring allgather phase: ws-1 duplex steps circulating the fully-reduced
  // chunks over `ring`; with shift s, this rank's ring position starts
  // owning chunk (pos + 1 + s) mod ws.
  void ring_allgather_phase(uint8_t* bytes, const std::vector<size_t>& bounds,
                            size_t esz, int64_t shift, TimePoint deadline,
                            const std::vector<int64_t>& ring) {
    int64_t ws = static_cast<int64_t>(ring.size());
    int64_t pos = ring_pos(ring, rank_);
    int64_t right = ring[(pos + 1) % ws];
    int64_t left = ring[(pos - 1 + ws) % ws];
    auto chunk_ptr = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return bytes + bounds[i] * esz;
    };
    auto chunk_bytes = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return (bounds[i + 1] - bounds[i]) * esz;
    };
    std::vector<int> right_fds = peer_fds(right);
    std::vector<int> left_fds = peer_fds(left);
    for (int64_t step = 0; step < ws - 1; ++step) {
      int64_t send_idx = pos + 1 + shift - step;
      int64_t recv_idx = pos + shift - step;
      std::string send_err;
      std::thread sender([&] {
        try {
          send_striped(right_fds, right, 2000 + step, chunk_ptr(send_idx),
                       chunk_bytes(send_idx), deadline);
        } catch (const std::exception& e) {
          send_err = e.what();
        }
      });
      try {
        recv_striped(left_fds, left, 2000 + step, chunk_ptr(recv_idx),
                     chunk_bytes(recv_idx), deadline);
      } catch (...) {
        sender.join();
        throw;
      }
      sender.join();
      if (!send_err.empty()) throw CommError(send_err);
    }
  }

  // recv a frame in quanta, reducing each quantum into `dst` as it arrives
  // (TCP delivers in order, so progressive reduction needs only a
  // quantum-sized scratch and overlaps compute with the wire)
  void recv_framed_reduce(int fd, int64_t peer, uint64_t tag, void* dst,
                          size_t nbytes, uint8_t* scratch, DType dt, RedOp op,
                          TimePoint deadline) {
    static constexpr size_t kQuantum = size_t(4) << 20;
    uint64_t hdr[2];
    recv_loop(fd, peer, hdr, 16, deadline);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(peer));
    if (hdr[0] != nbytes)
      throw CommError("size mismatch from rank " + std::to_string(peer));
    size_t esz = dtype_size(dt);
    size_t quantum = kQuantum - (kQuantum % (esz ? esz : 1));
    uint8_t* d = static_cast<uint8_t*>(dst);
    size_t off = 0;
    while (off < nbytes) {
      size_t take = std::min(quantum, nbytes - off);
      recv_loop(fd, peer, scratch, take, deadline);
      reduce_buffer(d + off, scratch, take, dt, op);
      off += take;
    }
  }

  void recv_framed(int fd, int64_t peer, uint64_t tag, void* buf,
                   size_t nbytes, TimePoint deadline) {
    uint64_t hdr[2];
    recv_loop(fd, peer, hdr, 16, deadline);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(peer));
    if (hdr[0] != nbytes)
      throw CommError("size mismatch from rank " + std::to_string(peer));
    recv_loop(fd, peer, buf, nbytes, deadline);
  }

  void send_loop(int fd, int64_t peer, const void* buf, size_t n,
                 TimePoint deadline) {
    const uint8_t* p = static_cast<const uint8_t*>(buf);
    while (n > 0) {
      check_abort();
      if (now() > deadline) throw CommError("send timed out");
      ssize_t sent = ::send(fd, p, n, MSG_NOSIGNAL);
      if (sent < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;  // quantum expired: re-check abort/deadline
        throw CommError("send failed to rank " + std::to_string(peer));
      }
      p += sent;
      n -= static_cast<size_t>(sent);
    }
  }

  void recv_loop(int fd, int64_t peer, void* buf, size_t n, TimePoint deadline) {
    uint8_t* p = static_cast<uint8_t*>(buf);
    while (n > 0) {
      check_abort();
      if (now() > deadline) throw CommError("recv timed out");
      ssize_t got = ::recv(fd, p, n, 0);
      if (got == 0)
        throw CommError("connection to rank " + std::to_string(peer) + " closed");
      if (got < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;  // quantum expired: re-check abort/deadline
        throw CommError("recv failed from rank " + std::to_string(peer));
      }
      p += got;
      n -= static_cast<size_t>(got);
    }
  }

  // all-peers concurrent exchange (alltoall/allgather/broadcast fan-out):
  // one duplex worker per peer, each leg lane-striped.
  template <typename SendFn, typename RecvFn>
  void multi_exchange(const std::map<int64_t, std::vector<int>>& peers,
                      SendFn send_for, RecvFn recv_for, uint64_t tag,
                      TimePoint deadline) {
    std::vector<std::thread> workers;
    std::mutex err_mu;
    std::string first_err;
    for (const auto& [peer, fds] : peers) {
      auto [sb, sn] = send_for(peer);
      auto [rb, rn] = recv_for(peer);
      workers.emplace_back([this, peer = peer, fds = fds, sb, sn, rb, rn, tag,
                            deadline, &err_mu, &first_err] {
        try {
          if (rb == nullptr) {
            send_striped(fds, peer, tag, sb, sn, deadline);
            return;
          }
          std::string send_err;
          std::thread sender([&] {
            try {
              send_striped(fds, peer, tag, sb, sn, deadline);
            } catch (const std::exception& e) {
              send_err = e.what();
            }
          });
          try {
            recv_striped(fds, peer, tag, rb, rn, deadline);
          } catch (const std::exception& e) {
            sender.join();
            throw CommError(e.what());
          }
          sender.join();
          if (!send_err.empty()) throw CommError(send_err);
        } catch (const std::exception& e) {
          std::lock_guard<std::mutex> lock(err_mu);
          if (first_err.empty()) first_err = e.what();
        }
      });
    }
    for (auto& w : workers) w.join();
    if (!first_err.empty()) throw CommError(first_err);
  }

  double timeout_s_;
  int64_t rank_ = 0;
  int64_t world_size_ = 1;
  size_t lanes_ = 1;
  size_t stripe_floor_ = size_t(64) << 10;
  std::atomic<bool> aborted_{false};
  // guards peers_/graveyard_ STRUCTURE only — never held across IO; ops
  // snapshot the fds they need at entry (fds stay open until destruction,
  // so a snapshot can never dangle)
  mutable std::mutex state_mu_;
  std::map<int64_t, std::vector<int>> peers_;
  std::vector<int> graveyard_;
};

}  // namespace tpuft
