// Host-side data-plane communicator — C++ twin of the Python
// TCPCommunicator mesh tier (torchft_tpu/communicator.py), built for DCN
// throughput: blocking duplex IO on persistent per-lane worker threads,
// scatter-gather sendmsg/recvmsg framing (multi-buffer payloads are never
// assembled in a staging copy), -O3 vectorized reduction loops, ring
// allreduce (reduce-scatter + allgather), alltoall/allgather, broadcast,
// send/recv, and a token-bucket network emulator mirroring the Python
// tier's _NetEmu (same env knobs, same profiles) so cross-tier benches
// shape both planes identically.
//
// All ops are synchronous at this level and abortable: abort() flips a flag
// and shuts the sockets down, unblocking any op mid-IO (the userspace
// timeout/abort doctrine, SURVEY.md §5.8.5).  The Python wrapper
// (torchft_tpu/native.py CppCommunicator) serializes ops on an op thread
// and layers Work/timeout semantics on top.

#pragma once

#include <fcntl.h>
#include <sys/socket.h>
#include <sys/uio.h>

#include <algorithm>
#include <array>
#include <atomic>
#include <cctype>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "store.h"
#include "wire.h"

namespace tpuft {

enum DType : int32_t {
  DT_F32 = 0,
  DT_F64 = 1,
  DT_I32 = 2,
  DT_I64 = 3,
  DT_BF16 = 4,
  DT_U8 = 5,
  DT_I8 = 6,
};

enum RedOp : int32_t { OP_SUM = 0, OP_MAX = 1, OP_MIN = 2 };

inline size_t dtype_size(DType dt) {
  switch (dt) {
    case DT_F64:
    case DT_I64:
      return 8;
    case DT_F32:
    case DT_I32:
      return 4;
    case DT_BF16:
      return 2;
    default:
      return 1;
  }
}

inline float bf16_to_f32(uint16_t v) {
  uint32_t bits = static_cast<uint32_t>(v) << 16;
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

inline uint16_t f32_to_bf16(float f) {
  uint32_t bits;
  std::memcpy(&bits, &f, 4);
  // round-to-nearest-even
  uint32_t rounding = 0x7FFF + ((bits >> 16) & 1);
  return static_cast<uint16_t>((bits + rounding) >> 16);
}

template <typename T>
inline void reduce_typed(T* acc, const T* in, size_t n, RedOp op) {
  switch (op) {
    case OP_SUM:
      for (size_t i = 0; i < n; ++i) acc[i] += in[i];
      break;
    case OP_MAX:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] > in[i] ? acc[i] : in[i];
      break;
    case OP_MIN:
      for (size_t i = 0; i < n; ++i) acc[i] = acc[i] < in[i] ? acc[i] : in[i];
      break;
  }
}

inline void reduce_buffer(void* acc, const void* in, size_t nbytes, DType dt,
                          RedOp op) {
  switch (dt) {
    case DT_F32:
      reduce_typed(static_cast<float*>(acc), static_cast<const float*>(in),
                   nbytes / 4, op);
      break;
    case DT_F64:
      reduce_typed(static_cast<double*>(acc), static_cast<const double*>(in),
                   nbytes / 8, op);
      break;
    case DT_I32:
      reduce_typed(static_cast<int32_t*>(acc), static_cast<const int32_t*>(in),
                   nbytes / 4, op);
      break;
    case DT_I64:
      reduce_typed(static_cast<int64_t*>(acc), static_cast<const int64_t*>(in),
                   nbytes / 8, op);
      break;
    case DT_I8:
      reduce_typed(static_cast<int8_t*>(acc), static_cast<const int8_t*>(in),
                   nbytes, op);
      break;
    case DT_U8:
      reduce_typed(static_cast<uint8_t*>(acc), static_cast<const uint8_t*>(in),
                   nbytes, op);
      break;
    case DT_BF16: {
      auto* a = static_cast<uint16_t*>(acc);
      auto* b = static_cast<const uint16_t*>(in);
      size_t n = nbytes / 2;
      for (size_t i = 0; i < n; ++i) {
        float fa = bf16_to_f32(a[i]);
        float fb = bf16_to_f32(b[i]);
        float out = op == OP_SUM   ? fa + fb
                    : op == OP_MAX ? (fa > fb ? fa : fb)
                                   : (fa < fb ? fa : fb);
        a[i] = f32_to_bf16(out);
      }
      break;
    }
  }
}

struct CommError : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// --- network emulation (mirror of communicator._NetEmu) ---------------------
//
// Deterministic sender-side pacing behind the SAME env knobs as the Python
// tier — TORCHFT_NET_EMU (named profile), TORCHFT_NET_GBPS /
// TORCHFT_NET_RTT_MS (raw overrides), TORCHFT_NET_CWND_KB (per-stream
// congestion-window cap) — so a cross-tier bench shapes both planes with
// one model: a process-shared link token bucket (one process = one
// emulated host NIC), a per-connection cwnd-limited stream bucket, and a
// half-RTT gate before each frame's first byte.  Profile names and values
// must match communicator._NET_EMU_PROFILES exactly (ftlint native-mirror
// checks them).

struct NetProfile {
  const char* name;
  double gbps;
  double rtt_ms;
};

// (name, link Gbit/s, RTT ms) — mirror of communicator._NET_EMU_PROFILES
constexpr NetProfile kNetEmuProfiles[] = {
    {"wan_1g", 1.0, 10.0},     {"wan_1g_10ms", 1.0, 10.0},
    {"dcn_10g", 10.0, 2.0},    {"dcn_10g_2ms", 10.0, 2.0},
    {"loopback", 0.0, 0.0},
};

class Pacer {
 public:
  // capped-accrual token bucket, the _StreamBucket math verbatim
  struct Bucket {
    double rate = 0.0;
    double burst = 0.0;
    double tokens = 0.0;
    std::chrono::steady_clock::time_point last;

    Bucket() = default;
    Bucket(double r, double b)
        : rate(r), burst(b), tokens(b), last(std::chrono::steady_clock::now()) {}

    size_t allow(size_t want) {
      auto now = std::chrono::steady_clock::now();
      tokens = std::min(
          burst, tokens + std::chrono::duration<double>(now - last).count() * rate);
      last = now;
      double cap = tokens < 0 ? 0.0 : tokens;
      return static_cast<size_t>(
          std::min<double>(static_cast<double>(want), cap));
    }
    void consume(size_t n) { tokens -= static_cast<double>(n); }
  };

  Pacer(double gbps, double rtt_ms, size_t cwnd_bytes)
      : bytes_per_s_(gbps * 1e9 / 8.0),
        rtt_s_(rtt_ms / 1e3),
        half_rtt_s_(rtt_ms / 2e3),
        cwnd_bytes_(cwnd_bytes) {
    stream_bytes_per_s_ = (cwnd_bytes_ > 0 && rtt_s_ > 0)
                              ? static_cast<double>(cwnd_bytes_) / rtt_s_
                              : 0.0;
    if (bytes_per_s_ > 0) {
      double burst = std::max<double>(64 << 10, bytes_per_s_ * 0.005);
      link_ = shared_link(bytes_per_s_, burst);
    }
  }

  // parse TORCHFT_NET_EMU / TORCHFT_NET_GBPS / TORCHFT_NET_RTT_MS /
  // TORCHFT_NET_CWND_KB; nullptr when unshaped.  An unknown profile is
  // LOUD (like the Python tier): a typo'd profile must not record
  // loopback numbers as a DCN run.
  static std::unique_ptr<Pacer> from_env() {
    const char* raw = std::getenv("TORCHFT_NET_EMU");
    std::string profile = raw ? raw : "";
    // strip + lowercase exactly like the Python _net_emu_from_env: a
    // trailing space from a YAML export must not fail only one tier
    while (!profile.empty() && std::isspace(profile.front()))
      profile.erase(profile.begin());
    while (!profile.empty() && std::isspace(profile.back()))
      profile.pop_back();
    std::transform(profile.begin(), profile.end(), profile.begin(), ::tolower);
    double prof_gbps = 0.0, prof_rtt = 0.0;
    if (!profile.empty()) {
      bool found = false;
      for (const auto& p : kNetEmuProfiles) {
        if (profile == p.name) {
          prof_gbps = p.gbps;
          prof_rtt = p.rtt_ms;
          found = true;
          break;
        }
      }
      if (!found)
        throw CommError("unknown TORCHFT_NET_EMU profile '" + profile + "'");
    }
    double gbps = env_double("TORCHFT_NET_GBPS", prof_gbps);
    double rtt_ms = env_double("TORCHFT_NET_RTT_MS", prof_rtt);
    size_t cwnd =
        static_cast<size_t>(env_double("TORCHFT_NET_CWND_KB", 256.0) * 1024);
    if (gbps <= 0 && rtt_ms <= 0) return nullptr;
    return std::make_unique<Pacer>(gbps, rtt_ms, cwnd);
  }

  double half_rtt_s() const { return half_rtt_s_; }
  double rtt_s() const { return rtt_s_; }
  double bytes_per_s() const { return bytes_per_s_; }
  double stream_bytes_per_s() const { return stream_bytes_per_s_; }

  // the largest grant allow() can ever return (the tightest engaged
  // bucket's burst) — callers batching paced sends must not wait for more
  size_t max_grant() const {
    double cap = 1e18;
    if (link_)
      cap = std::min(cap, std::max<double>(64 << 10, bytes_per_s_ * 0.005));
    if (stream_bytes_per_s_ > 0)
      cap = std::min(cap, static_cast<double>(cwnd_bytes_));
    return static_cast<size_t>(cap);
  }

  // RTT x bandwidth product — the natural frame size on this profile
  size_t bdp_bytes() const {
    if (bytes_per_s_ <= 0 || rtt_s_ <= 0) return 0;
    return static_cast<size_t>(bytes_per_s_ * rtt_s_);
  }

  // bytes the link (and, when RTT emulation is on, `stream`'s cwnd bucket)
  // permit right now (<= want); stream is the connection identity (its fd)
  size_t allow(size_t want, uint64_t stream) {
    if (link_) {
      std::lock_guard<std::mutex> lock(link_->mu);
      want = link_->bucket.allow(want);
    }
    if (stream_bytes_per_s_ > 0 && want > 0) {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = streams_.find(stream);
      if (it == streams_.end())
        it = streams_
                 .emplace(stream, Bucket(stream_bytes_per_s_,
                                         static_cast<double>(cwnd_bytes_)))
                 .first;
      want = it->second.allow(want);
    }
    return want;
  }

  void consume(size_t n, uint64_t stream) {
    if (link_) {
      std::lock_guard<std::mutex> lock(link_->mu);
      link_->bucket.consume(n);
    }
    if (stream_bytes_per_s_ > 0) {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = streams_.find(stream);
      if (it != streams_.end()) it->second.consume(n);
    }
  }

 private:
  struct Link {
    std::mutex mu;
    Bucket bucket;
  };

  // the LINK bucket is process-shared (one process = one emulated host
  // NIC, communicator._LinkBucket): every communicator in the process
  // draws from the same bucket keyed by the link parameters
  static Link* shared_link(double rate, double burst) {
    static std::mutex registry_mu;
    static std::map<std::pair<double, double>, std::unique_ptr<Link>> registry;
    std::lock_guard<std::mutex> lock(registry_mu);
    auto key = std::make_pair(rate, burst);
    auto it = registry.find(key);
    if (it == registry.end()) {
      auto link = std::make_unique<Link>();
      link->bucket = Bucket(rate, burst);
      it = registry.emplace(key, std::move(link)).first;
    }
    return it->second.get();
  }

  static double env_double(const char* name, double fallback) {
    const char* v = std::getenv(name);
    if (!v || !*v) return fallback;
    char* end = nullptr;
    double out = std::strtod(v, &end);
    if (end == v)
      throw CommError(std::string("unparseable ") + name + "=" + v);
    return out;
  }

  double bytes_per_s_;
  double rtt_s_;
  double half_rtt_s_;
  size_t cwnd_bytes_;
  double stream_bytes_per_s_ = 0.0;
  Link* link_ = nullptr;
  std::mutex mu_;
  std::map<uint64_t, Bucket> streams_;
};

// Parallel-connection ("lane") config for striped collectives — must agree
// with the Python tier (torchft_tpu/communicator.py _ring_lanes /
// _stripe_floor) and be uniform across ranks (verified in the rendezvous
// hello).  "auto" resolves exactly like the Python tier: enough lanes that
// the aggregate cwnd-limited stream rate reaches the emulated link rate
// (capped at kMaxAutoLanes), 1 when unshaped.
constexpr size_t kMaxAutoLanes = 4;  // mirror of communicator._MAX_AUTO_LANES
constexpr size_t kMinStripeBytes =
    size_t(64) << 10;  // mirror of communicator._MIN_STRIPE_BYTES

inline size_t ring_lanes_from_env(const Pacer* pacer) {
  const char* v = std::getenv("TORCHFT_RING_LANES");
  if (v && *v && std::string(v) != "auto") {
    long n = std::strtol(v, nullptr, 10);
    return n >= 1 ? static_cast<size_t>(n) : 1;
  }
  if (!pacer || pacer->stream_bytes_per_s() <= 0 || pacer->bytes_per_s() <= 0)
    return 1;
  size_t link = static_cast<size_t>(pacer->bytes_per_s());
  size_t stream =
      std::max<size_t>(1, static_cast<size_t>(pacer->stream_bytes_per_s()));
  size_t need = (link + stream - 1) / stream;
  return std::max<size_t>(1, std::min(kMaxAutoLanes, need));
}

inline size_t stripe_floor_from_env(const Pacer* pacer) {
  const char* v = std::getenv("TORCHFT_RING_FRAME_KB");
  if (v && *v && std::string(v) != "auto") {
    double kb = std::strtod(v, nullptr);
    size_t b = static_cast<size_t>(kb * 1024);
    return b < 64 ? 64 : b;
  }
  if (pacer) {
    size_t bdp = pacer->bdp_bytes();
    if (bdp > 0)
      // jumbo frames on DCN: one sub-frame covers at least a BDP so the
      // half-RTT frame gate amortizes (mirror of communicator._stripe_floor)
      return std::max(kMinStripeBytes, std::min(bdp, size_t(8) << 20));
  }
  return kMinStripeBytes;
}

// --- scatter-gather framing --------------------------------------------------
//
// One logical frame may be backed by MANY caller buffers (a gradient
// bucket's arrays, quantized rows + scales, chunked outer shards).  The
// iovec plumbing below sends and receives such frames with sendmsg /
// recvmsg straight against the callers' memory — the payload is never
// assembled in a staging copy on either side.

// max payload iovec segments per sendmsg/recvmsg call (the header rides as
// one more); bounded well under IOV_MAX.  Mirrored in native.py
// (_MAX_IOV_SEGS) so the binding's segment batching agrees.
constexpr size_t kMaxIovSegs = 64;

// paced sends coalesce token dribbles: below this floor (clamped to half
// the pacer's max grant) the sender naps briefly instead of issuing a
// sendmsg per few-KB accrual — the nap is short enough that the bucket
// (whose burst is at least twice the floor) never tops out and wastes
// tokens even when a loaded host oversleeps
constexpr size_t kPaceMinSendBytes = 32 << 10;

// Walks a logical byte range expressed as iovec segments; fill() emits a
// bounded iovec batch for one sendmsg/recvmsg, advance() consumes it.
class IovCursor {
 public:
  IovCursor() = default;
  explicit IovCursor(std::vector<struct iovec> iov) : iov_(std::move(iov)) {
    for (const auto& v : iov_) remaining_ += v.iov_len;
  }

  size_t remaining() const { return remaining_; }

  // fill up to max_segs entries covering at most max_bytes, starting at
  // the cursor; returns the entry count (0 when exhausted or clamped)
  int fill(struct iovec* out, size_t max_segs, size_t max_bytes) const {
    size_t idx = idx_, off = off_, budget = max_bytes;
    size_t cnt = 0;
    while (idx < iov_.size() && cnt < max_segs && budget > 0) {
      uint8_t* base = static_cast<uint8_t*>(iov_[idx].iov_base) + off;
      size_t len = std::min(iov_[idx].iov_len - off, budget);
      if (len == 0) break;
      out[cnt].iov_base = base;
      out[cnt].iov_len = len;
      ++cnt;
      budget -= len;
      ++idx;
      off = 0;
    }
    return static_cast<int>(cnt);
  }

  void advance(size_t n) {
    remaining_ -= n;
    while (n > 0) {
      size_t left = iov_[idx_].iov_len - off_;
      if (n < left) {
        off_ += n;
        return;
      }
      n -= left;
      ++idx_;
      off_ = 0;
    }
  }

 private:
  std::vector<struct iovec> iov_;
  size_t idx_ = 0;
  size_t off_ = 0;
  size_t remaining_ = 0;
};

// A logical contiguous byte space backed by scattered segments (one per
// caller buffer).  Ring chunk math runs over LOGICAL offsets; the IO layer
// resolves them to segment slices at the syscall boundary.  Segment
// boundaries fall between whole arrays of one dtype, so an element never
// straddles segments and per-segment reduction is exact.
class ScatterView {
 public:
  ScatterView(void* data, size_t nbytes) : total_(nbytes) {
    segs_.emplace_back(static_cast<uint8_t*>(data), nbytes);
    starts_.push_back(0);
  }

  ScatterView(void* const* bufs, const uint64_t* lens, size_t n) {
    size_t off = 0;
    for (size_t i = 0; i < n; ++i) {
      if (lens[i] == 0) continue;
      segs_.emplace_back(static_cast<uint8_t*>(bufs[i]),
                         static_cast<size_t>(lens[i]));
      starts_.push_back(off);
      off += lens[i];
    }
    total_ = off;
  }

  size_t size() const { return total_; }

  // iovec list covering logical [off, off+len)
  std::vector<struct iovec> slice(size_t off, size_t len) const {
    std::vector<struct iovec> out;
    if (len == 0) return out;
    size_t i = seg_at(off);
    while (len > 0) {
      size_t seg_off = off - starts_[i];
      size_t take = std::min(segs_[i].second - seg_off, len);
      out.push_back({segs_[i].first + seg_off, take});
      off += take;
      len -= take;
      ++i;
    }
    return out;
  }

  // pointer when [off, off+len) lies inside ONE segment, else nullptr
  uint8_t* contiguous(size_t off, size_t len) const {
    size_t i = seg_at(off);
    size_t seg_off = off - starts_[i];
    if (segs_[i].second - seg_off >= len) return segs_[i].first + seg_off;
    return nullptr;
  }

  // acc[off : off+len] ?= src, segment crossings handled (boundaries are
  // element-aligned by construction)
  void reduce_in(size_t off, const void* src, size_t len, DType dt, RedOp op) {
    const uint8_t* s = static_cast<const uint8_t*>(src);
    size_t i = seg_at(off);
    while (len > 0) {
      size_t seg_off = off - starts_[i];
      size_t take = std::min(segs_[i].second - seg_off, len);
      reduce_buffer(segs_[i].first + seg_off, s, take, dt, op);
      s += take;
      off += take;
      len -= take;
      ++i;
    }
  }

 private:
  size_t seg_at(size_t off) const {
    // binary search the covering segment
    size_t lo = 0, hi = starts_.size();
    while (lo + 1 < hi) {
      size_t mid = (lo + hi) / 2;
      if (starts_[mid] <= off)
        lo = mid;
      else
        hi = mid;
    }
    return lo;
  }

  std::vector<std::pair<uint8_t*, size_t>> segs_;
  std::vector<size_t> starts_;
  size_t total_ = 0;
};

// --- per-lane worker threads -------------------------------------------------
//
// One persistent tx and one persistent rx worker per (peer, lane) link,
// replacing the short-lived thread spawns of the round-1 build (a thread
// create + join per frame part per ring step).  Workers are created
// lazily at first use, live for the epoch, and drain with errors after
// abort() (sockets are shut down, so blocked IO returns immediately).

class LanePool {
 public:
  static constexpr int kTx = 0;
  static constexpr int kRx = 1;

  ~LanePool() { shutdown(); }

  void submit(int64_t peer, size_t lane, int dir, std::function<void()> fn) {
    // shared_ptr, not a raw pointer: shutdown() (a foreign thread's
    // configure() superseding this epoch) may join AND DESTROY the worker
    // between our mu_ release and the w->mu acquire below — the copy keeps
    // the Worker alive until this submit is done with it
    std::shared_ptr<Worker> w;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (!stopped_) {
        uint64_t key = (static_cast<uint64_t>(peer) << 16) |
                       (static_cast<uint64_t>(lane & 0x7FFF) << 1) |
                       static_cast<uint64_t>(dir & 1);
        auto it = workers_.find(key);
        if (it == workers_.end()) {
          it = workers_.emplace(key, std::make_shared<Worker>()).first;
          Worker* raw = it->second.get();
          raw->th = std::thread([raw] { raw->run(); });
        }
        w = it->second;
      }
    }
    if (w == nullptr) {
      // pool already stopped (epoch superseded): run inline — the task
      // fails fast against the shut-down sockets, releasing its latch
      fn();
      return;
    }
    {
      std::lock_guard<std::mutex> lock(w->mu);
      if (!w->stop) {
        w->q.push_back(std::move(fn));
        w->cv.notify_one();
        return;
      }
      // shutdown() won the race between our stopped_ check and this
      // enqueue: the worker may already have drained and exited, so a
      // task pushed now would sit in the queue forever and its latch
      // would never release — run inline instead (fails fast like the
      // pool-stopped path above)
    }
    fn();
  }

  void shutdown() {
    std::map<uint64_t, std::shared_ptr<Worker>> workers;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (stopped_) return;
      stopped_ = true;
      workers.swap(workers_);
    }
    for (auto& [key, w] : workers) {
      {
        std::lock_guard<std::mutex> lock(w->mu);
        w->stop = true;
      }
      w->cv.notify_all();
      if (w->th.joinable()) w->th.join();
    }
  }

 private:
  struct Worker {
    std::thread th;
    std::mutex mu;
    std::condition_variable cv;
    std::deque<std::function<void()>> q;
    bool stop = false;

    void run() {
      while (true) {
        std::function<void()> fn;
        {
          std::unique_lock<std::mutex> lock(mu);
          cv.wait(lock, [&] { return stop || !q.empty(); });
          if (q.empty()) return;  // stop requested and drained
          fn = std::move(q.front());
          q.pop_front();
        }
        fn();
      }
    }
  };

  std::mutex mu_;
  bool stopped_ = false;
  std::map<uint64_t, std::shared_ptr<Worker>> workers_;
};

// completion latch for a fan-out of lane tasks; collects the first error
struct OpLatch {
  std::mutex mu;
  std::condition_variable cv;
  size_t pending = 0;
  std::string err;

  void add(size_t n) {
    std::lock_guard<std::mutex> lock(mu);
    pending += n;
  }
  void done(const std::string& e) {
    std::lock_guard<std::mutex> lock(mu);
    if (!e.empty() && err.empty()) err = e;
    if (--pending == 0) cv.notify_all();
  }
  // wait without throwing; returns the first error ("" when clean)
  std::string wait_quiet() {
    std::unique_lock<std::mutex> lock(mu);
    cv.wait(lock, [&] { return pending == 0; });
    return err;
  }
  void wait() {
    std::string e = wait_quiet();
    if (!e.empty()) throw CommError(e);
  }
};

// --- hierarchical topology (leader ring) ------------------------------------
//
// Mirror of the Python tier's host grouping (communicator.py _HostTopology)
// so the tiers agree on the hierarchical WIRE SCHEDULE: hosts are ordered
// by their SMALLEST global rank, each host's leader IS that rank, and
// cross-host collectives run over the leader ring in that order (ring
// position replaces rank in the chunk schedule — see the `ring` parameter
// of ring_reduce_phase / ring_allgather_phase).  The shared-memory
// intra-host hop is host-local and never crosses tiers.  NOTE: this tier's
// configure() does not yet publish `topo_{rank}` keys, so a native rank in
// a group makes the Python ranks' "auto" fall back to the flat ring (and a
// forced TORCHFT_HIERARCHICAL=1 fail loudly); these helpers pin the math a
// full native topology integration must reproduce byte-for-byte.
// (tier.py data_plane_tier() downgrades auto-mode native selection to the
// Python tier whenever hierarchical dispatch is forced on, logging it.)

// TORCHFT_HIERARCHICAL: "auto" (default) | "0" | "1" — must be uniform
// across replicas, like TORCHFT_RING_LANES.
inline std::string hierarchical_mode_from_env() {
  const char* v = std::getenv("TORCHFT_HIERARCHICAL");
  std::string s = v ? v : "auto";
  if (s.empty() || s == "auto") return "auto";
  if (s == "1" || s == "true" || s == "on") return "1";
  if (s == "0" || s == "false" || s == "off") return "0";
  throw CommError("unparseable TORCHFT_HIERARCHICAL=" + s + " (auto|0|1)");
}

// TORCHFT_HOST_ID overrides the host identity (default: the advertised
// rendezvous address' host part — same-IP grouping).
inline std::string host_id_from_env(const std::string& fallback) {
  const char* v = std::getenv("TORCHFT_HOST_ID");
  return (v && *v) ? std::string(v) : fallback;
}

struct HostTopology {
  std::vector<std::vector<int64_t>> hosts;  // ordered by min global rank
  std::vector<int64_t> leader_ring;         // hosts[i][0] for each host

  // identical grouping math to the Python tier: ranks ascend within a
  // host, hosts order by their first (smallest) rank
  static HostTopology build(const std::map<int64_t, std::string>& host_of) {
    std::map<std::string, std::vector<int64_t>> groups;
    for (const auto& kv : host_of) groups[kv.second].push_back(kv.first);
    HostTopology t;
    for (const auto& kv : groups) t.hosts.push_back(kv.second);
    std::sort(t.hosts.begin(), t.hosts.end(),
              [](const std::vector<int64_t>& a, const std::vector<int64_t>& b) {
                return a.front() < b.front();
              });
    for (const auto& g : t.hosts) t.leader_ring.push_back(g.front());
    return t;
  }

  // the "auto" criterion, mirrored: >= 2 hosts AND a multi-member host
  bool worth_it() const {
    if (hosts.size() < 2) return false;
    for (const auto& g : hosts)
      if (g.size() > 1) return true;
    return false;
  }
};

// High bit of the hello's rank field marks the extended (multi-lane) hello:
// (rank|flag, lane, lane count, stripe floor).  Must match the Python
// tier's _LANE_HELLO_FLAG.
constexpr uint64_t kLaneHelloFlag = uint64_t(1) << 63;

// Explicit reduce_scatter API calls ride their own tag window, clear of
// the allreduce rings — mirror of wire.RING_REDUCE_TAG_BASE (the round-1
// build framed them at tag base 0, colliding with a Python peer's 30000
// window; mixed-tier meshes now pin this).
constexpr uint64_t kRingReduceTagBase = 30000;

// Flight-recorder event ids, mirror of the data-plane block of
// obs/flight.py FlightEvent (the ftlint native-mirror checker pins every
// kFlight* value against the Python enum).  The native tier records its
// epoch lifecycle into a fixed-slot ring drained into the Python dump via
// tpuft_comm_flight_drain.
constexpr uint32_t kFlightCommConfigure = 20;
constexpr uint32_t kFlightCommAbort = 21;
constexpr size_t kFlightRingSlots = 256;

// one C-side flight event: monotonic stamp (steady_clock seconds — the
// same CLOCK_MONOTONIC base as Python time.monotonic() on Linux) plus two
// small integer payload fields (rank/world for configure)
struct FlightSlot {
  uint64_t seq = 0;
  double t = 0.0;
  uint32_t ev = 0;
  int64_t a = 0;
  int64_t b = 0;
};

// Per-epoch IO state: the pacer, the per-lane counters, and the lane
// config they index.  Ops snapshot ONE shared_ptr at entry — configure()
// swaps in a fresh instance while a superseded op thread may still be
// mid-IO on the old epoch's state, and the shared_ptr keeps that state
// alive exactly as long as any late op references it (the same doctrine
// as the fd graveyard, without unbounded growth or torn pointer reads).
struct EpochIO {
  std::unique_ptr<Pacer> pacer;
  size_t lanes = 1;
  size_t stripe_floor = kMinStripeBytes;
  // the epoch's identity rides the snapshot too: an op body that read
  // rank_/world_size_ more than once could see configure() move them
  // between loads (size a vector from the old world, index it with the
  // new one — an out-of-bounds write, not just a stale value).  One
  // io_snapshot() at op entry yields all-or-nothing epoch state.
  int64_t rank = 0;
  int64_t world = 1;
  // per-lane observability: payload bytes moved and stall events (pacer
  // denials / kernel would-block), names mirroring _TcpMesh lane_tx_bytes
  // / lane_rx_bytes / lane_stalls
  std::unique_ptr<std::atomic<uint64_t>[]> tx, rx, stalls;

  void alloc_counters() {
    tx.reset(new std::atomic<uint64_t>[lanes]());
    rx.reset(new std::atomic<uint64_t>[lanes]());
    stalls.reset(new std::atomic<uint64_t>[lanes]());
  }
  void stall(size_t lane) {
    if (stalls && lane < lanes)
      stalls[lane].fetch_add(1, std::memory_order_relaxed);
  }
  void add_tx(size_t lane, size_t n) {
    if (tx && lane < lanes) tx[lane].fetch_add(n, std::memory_order_relaxed);
  }
  void add_rx(size_t lane, size_t n) {
    if (rx && lane < lanes) rx[lane].fetch_add(n, std::memory_order_relaxed);
  }

  // half-RTT gate before a frame's first byte (mirror of the Python
  // exchange loop's frame_gates) — the pacer's RTT model, not a stall
  void gate() const {
    if (!pacer || pacer->half_rtt_s() <= 0) return;
    std::this_thread::sleep_for(
        std::chrono::duration<double>(pacer->half_rtt_s()));
  }

  // deterministic per-lane split of one frame; identical math to the
  // Python tier (_lane_parts) — see Communicator::lane_parts
  std::vector<std::pair<size_t, size_t>> lane_parts(size_t nbytes) const {
    if (lanes <= 1 || nbytes < 2 * stripe_floor) return {{0, nbytes}};
    size_t k = std::min(lanes, std::max<size_t>(1, nbytes / stripe_floor));
    if (k <= 1) return {{0, nbytes}};
    std::vector<size_t> bounds{0};
    for (size_t i = 1; i < k; ++i) {
      size_t cut = (i * nbytes / k) / 64 * 64;
      bounds.push_back(std::max(cut, bounds.back()));
    }
    bounds.push_back(nbytes);
    std::vector<std::pair<size_t, size_t>> parts;
    for (size_t i = 0; i < k; ++i) parts.emplace_back(bounds[i], bounds[i + 1]);
    return parts;
  }
};

using IoPtr = std::shared_ptr<EpochIO>;

class Communicator {
 public:
  explicit Communicator(double timeout_s)
      : timeout_s_(timeout_s), io_(std::make_shared<EpochIO>()) {}

  ~Communicator() {
    abort();
    {
      std::shared_ptr<LanePool> pool;
      {
        std::lock_guard<std::mutex> lock(state_mu_);
        pool = std::move(pool_);
      }
      if (pool) pool->shutdown();
    }
    close_peers();
  }

  // Rendezvous over the store: publish our listener under "{prefix}/{rank}";
  // for each pair (i, j) with i < j, j dials i — once per LANE.  Lanes are
  // parallel TCP connections one logical collective stripes frames across
  // (lane_parts); the Python tier (_TcpMesh) speaks the identical protocol:
  // legacy 8-byte hello (rank) at 1 lane, 32-byte `(rank|flag, lane, lane
  // count, stripe floor)` hello otherwise, lane count verified loudly.
  // store_prefixed_addr is "host:port/prefix/..." exactly like the Python
  // tier.
  void configure(const std::string& store_prefixed_addr, int64_t rank,
                 int64_t world_size) {
    abort();  // supersede any previous epoch
    std::shared_ptr<LanePool> old_pool;
    {
      // old fds go to the graveyard (closed at destruction): an op thread
      // may still reference them, and closing now could recycle fd numbers
      std::lock_guard<std::mutex> lock(state_mu_);
      for (auto& [peer, fds] : peers_)
        for (int fd : fds) graveyard_.push_back(fd);
      peers_.clear();
      old_pool = std::move(pool_);
    }
    // join the superseded epoch's lane workers: their sockets are shut
    // down, so any in-flight task errors out within one IO quantum
    if (old_pool) old_pool->shutdown();
    // fresh per-epoch IO state; a superseded op thread keeps the OLD
    // instance alive through its own shared_ptr snapshot.  NOTHING is
    // published until the rendezvous is complete: ops racing configure()
    // keep failing fast on the latched abort + the old (cleared) peers
    // instead of seeing a half-built epoch (e.g. the new rank with the
    // old caller's buffer sizes), and abort is un-latched only after the
    // whole epoch — io, pool, peers — lands in one lock section.
    auto io = std::make_shared<EpochIO>();
    io->pacer = Pacer::from_env();
    io->lanes = ring_lanes_from_env(io->pacer.get());
    io->stripe_floor = stripe_floor_from_env(io->pacer.get());
    io->rank = rank;
    io->world = world_size;
    io->alloc_counters();
    const size_t lanes = io->lanes;
    const size_t stripe_floor = io->stripe_floor;
    auto publish = [&](std::map<int64_t, std::vector<int>> peers) {
      {
        std::lock_guard<std::mutex> lock(state_mu_);
        io_ = std::move(io);
        pool_ = std::make_shared<LanePool>();
        peers_ = std::move(peers);
      }
      lanes_ = lanes;
      stripe_floor_ = stripe_floor;
      rank_ = rank;
      world_size_ = world_size;
      aborted_ = false;
      flight_epochs_.fetch_add(1);
      flight_record(kFlightCommConfigure, rank, world_size);
    };
    if (world_size <= 1) {
      publish({});
      return;
    }

    auto slash = store_prefixed_addr.find('/');
    std::string store_addr = store_prefixed_addr.substr(0, slash);
    std::string prefix = slash == std::string::npos
                             ? std::string("root")
                             : store_prefixed_addr.substr(slash + 1);

    StoreClient store(store_addr, timeout_s_);

    int port = 0;
    int listen_fd = listen_on("0.0.0.0:0", &port);
    char host[256];
    ::gethostname(host, sizeof(host));
    std::string host_str(host);
    {
      // prefer a dialable address even on hosts with odd hostname setup
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      if (::getaddrinfo(host_str.c_str(), nullptr, &hints, &res) != 0 || !res)
        host_str = "127.0.0.1";
      if (res) ::freeaddrinfo(res);
    }
    store.set(prefix + "/" + std::to_string(rank),
              host_str + ":" + std::to_string(port));

    // accept from higher ranks on a helper thread while dialing lower ranks
    int expected_inbound =
        static_cast<int>((world_size - rank - 1) * lanes);
    std::map<int64_t, std::vector<int>> inbound;
    std::string accept_err;
    // bound the whole accept phase: a dead higher-rank peer must not wedge
    // configure() (the Python twin sets listener.settimeout(timeout_s))
    set_recv_timeout(listen_fd, timeout_s_);
    std::thread acceptor([&] {
      try {
        for (int i = 0; i < expected_inbound; ++i) {
          int conn = ::accept(listen_fd, nullptr, nullptr);
          if (conn < 0)
            throw CommError("rendezvous accept timed out or failed");
          configure_socket(conn);
          set_recv_timeout(conn, timeout_s_);
          uint64_t first;
          recv_exact(conn, &first, 8);
          if (!(first & kLaneHelloFlag)) {
            // legacy 8-byte hello: a single-lane peer.  A lane mismatch is
            // a config error — fail LOUDLY instead of desynchronizing.
            if (lanes != 1)
              throw CommError(
                  "lane-count mismatch: rank " + std::to_string(first) +
                  " has 1 lane, we have " + std::to_string(lanes) +
                  " (TORCHFT_RING_LANES must be uniform)");
            auto& fds = inbound[static_cast<int64_t>(first)];
            fds.assign(1, conn);
          } else {
            uint64_t tail[3];  // lane, lane count, stripe floor
            recv_exact(conn, tail, 24);
            uint64_t peer_rank = first & ~kLaneHelloFlag;
            if (tail[1] != lanes)
              throw CommError(
                  "lane-count mismatch: rank " + std::to_string(peer_rank) +
                  " has " + std::to_string(tail[1]) + " lanes, we have " +
                  std::to_string(lanes) +
                  " (TORCHFT_RING_LANES must be uniform)");
            if (tail[2] != stripe_floor)
              throw CommError(
                  "stripe-floor mismatch: rank " + std::to_string(peer_rank) +
                  " has " + std::to_string(tail[2]) + " bytes, we have " +
                  std::to_string(stripe_floor) +
                  " (TORCHFT_RING_FRAME_KB must be uniform)");
            if (tail[0] >= lanes)
              throw CommError(
                  "lane index out of range in hello from rank " +
                  std::to_string(peer_rank) + ": lane " +
                  std::to_string(tail[0]) + " >= " + std::to_string(lanes));
            auto& fds = inbound[static_cast<int64_t>(peer_rank)];
            if (fds.size() < lanes) fds.resize(lanes, -1);
            fds[tail[0]] = conn;
          }
        }
      } catch (const std::exception& e) {
        accept_err = e.what();
      }
    });

    std::map<int64_t, std::vector<int>> fresh;
    try {
      for (int64_t peer = 0; peer < rank; ++peer) {
        std::string addr =
            store.get(prefix + "/" + std::to_string(peer), timeout_s_);
        auto& fds = fresh[peer];
        for (size_t lane = 0; lane < lanes; ++lane) {
          int fd = dial(addr, timeout_s_);
          if (lanes == 1) {
            uint64_t my_rank = static_cast<uint64_t>(rank);
            send_all(fd, &my_rank, 8);
          } else {
            uint64_t hello[4] = {static_cast<uint64_t>(rank) | kLaneHelloFlag,
                                 lane, lanes, stripe_floor};
            send_all(fd, hello, 32);
          }
          fds.push_back(fd);
        }
      }
      acceptor.join();
      if (!accept_err.empty())
        throw CommError("rendezvous accept failed: " + accept_err);
      for (auto& [peer, fds] : inbound) fresh[peer] = fds;
    } catch (...) {
      if (acceptor.joinable()) acceptor.join();
      for (auto& [peer, fds] : fresh)
        for (int fd : fds) ::close(fd);
      ::close(listen_fd);
      throw;
    }
    ::close(listen_fd);

    for (auto& [peer, fds] : fresh) {
      for (int fd : fds) {
        // NB: no explicit SO_SNDBUF/SO_RCVBUF — setting them disables the
        // kernel's TCP buffer autotuning, which reaches larger effective
        // windows than the rmem/wmem_max caps allow explicitly
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        // blocking IO with a short timeout quantum: throughput of plain
        // send/recv, abort/deadline checks every quantum on EAGAIN
        timeval tv{0, 200000};  // 200ms
        ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
        ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
      }
    }
    publish(std::move(fresh));
  }

  void abort() {
    // Shut sockets down (don't close): an op thread may be mid-IO on these
    // fds; shutdown unblocks its IO with errors while keeping fd numbers
    // valid.  close happens at destruction.
    // flight: record the transition once per live epoch (configure() calls
    // abort() to supersede, so a bare flag write would log boot noise)
    if (!aborted_.exchange(true) && flight_epochs_.load() > 0)
      flight_record(kFlightCommAbort, 0, 0);
    std::lock_guard<std::mutex> lock(state_mu_);
    for (auto& [peer, fds] : peers_)
      for (int fd : fds) ::shutdown(fd, SHUT_RDWR);
  }

  // -- flight recorder (C-side fixed-slot ring; obs/flight.py merges it) ---

  void flight_record(uint32_t ev, int64_t a, int64_t b) {
    std::lock_guard<std::mutex> lock(flight_mu_);
    FlightSlot& slot = flight_[flight_seq_ % kFlightRingSlots];
    slot.seq = flight_seq_++;
    slot.t = std::chrono::duration<double>(
                 std::chrono::steady_clock::now().time_since_epoch())
                 .count();
    slot.ev = ev;
    slot.a = a;
    slot.b = b;
  }

  // Consume-drain the ring oldest-first into the caller's arrays (up to
  // `cap` events); already-drained and overwritten slots are skipped, so
  // repeated drains across dumps never duplicate an event.  Returns the
  // number of events copied.
  size_t flight_drain(uint64_t* seqs, double* ts, uint32_t* evs, int64_t* a,
                      int64_t* b, size_t cap) {
    std::lock_guard<std::mutex> lock(flight_mu_);
    uint64_t oldest =
        flight_seq_ > kFlightRingSlots ? flight_seq_ - kFlightRingSlots : 0;
    uint64_t start = std::max(flight_drained_, oldest);
    size_t n = 0;
    for (uint64_t s = start; s < flight_seq_ && n < cap; ++s, ++n) {
      const FlightSlot& slot = flight_[s % kFlightRingSlots];
      seqs[n] = slot.seq;
      ts[n] = slot.t;
      evs[n] = slot.ev;
      a[n] = slot.a;
      b[n] = slot.b;
    }
    flight_drained_ = start + n;
    return n;
  }

  void close_peers() {
    std::lock_guard<std::mutex> lock(state_mu_);
    for (auto& [peer, fds] : peers_)
      for (int fd : fds) ::close(fd);
    peers_.clear();
    for (int fd : graveyard_) ::close(fd);
    graveyard_.clear();
  }

  std::map<int64_t, std::vector<int>> peers_snapshot() const {
    std::lock_guard<std::mutex> lock(state_mu_);
    return peers_;
  }

  // deterministic per-lane split of one frame; identical math to the Python
  // tier (_lane_parts): both endpoints derive the split from the frame
  // length alone, 64-byte aligned so no element ever straddles lanes
  std::vector<std::pair<size_t, size_t>> lane_parts(size_t nbytes) const {
    size_t lanes = lanes_, stripe_floor = stripe_floor_;  // one read each
    if (lanes <= 1 || nbytes < 2 * stripe_floor) return {{0, nbytes}};
    size_t k = std::min(lanes, std::max<size_t>(1, nbytes / stripe_floor));
    if (k <= 1) return {{0, nbytes}};
    std::vector<size_t> bounds{0};
    for (size_t i = 1; i < k; ++i) {
      size_t cut = (i * nbytes / k) / 64 * 64;
      bounds.push_back(std::max(cut, bounds.back()));
    }
    bounds.push_back(nbytes);
    std::vector<std::pair<size_t, size_t>> parts;
    for (size_t i = 0; i < k; ++i) parts.emplace_back(bounds[i], bounds[i + 1]);
    return parts;
  }

  // deterministic per-replica shard split for the sharded outer optimizer;
  // identical math to the Python tier (communicator.outer_shard_parts): the
  // buffer is padded to a multiple of parts*unit and every shard is exactly
  // padded/parts bytes, so both tiers agree on shard ownership from the
  // payload size and participant count alone.  `unit` must be a positive
  // multiple of 64 (64 for raw f32 shards, the quantization row byte size
  // for int8 shards, so a boundary never splits a row).
  static std::vector<std::pair<size_t, size_t>> outer_shard_parts(
      size_t nbytes, size_t parts, size_t unit = 64) {
    if (parts < 1 || unit < 1 || unit % 64 != 0)
      throw std::invalid_argument("outer_shard_parts: bad parts/unit");
    size_t share = (nbytes + parts * unit - 1) / (parts * unit) * unit;
    std::vector<std::pair<size_t, size_t>> out;
    out.reserve(parts);
    for (size_t p = 0; p < parts; ++p)
      out.emplace_back(p * share, (p + 1) * share);
    return out;
  }

  int64_t rank() const { return rank_; }
  int64_t size() const { return world_size_; }
  size_t lanes() const { return lanes_; }
  size_t stripe_floor() const { return stripe_floor_; }
  void set_timeout(double t) { timeout_s_ = t; }

  // per-lane observability counters of the current epoch (payload bytes
  // moved + stall events: pacer denials / kernel would-block), the same
  // counters TCPCommunicator.lane_stats() exports — surfaced through
  // native.py so manager.last_quorum_timings is tier-agnostic.  Returns
  // the lane count; fills up to `cap` entries per array.
  size_t lane_stats(uint64_t* tx, uint64_t* rx, uint64_t* stalls,
                    size_t cap) const {
    IoPtr io = io_snapshot();
    if (!io->tx) return 0;
    for (size_t i = 0; i < std::min(io->lanes, cap); ++i) {
      tx[i] = io->tx[i].load(std::memory_order_relaxed);
      rx[i] = io->rx[i].load(std::memory_order_relaxed);
      stalls[i] = io->stalls[i].load(std::memory_order_relaxed);
    }
    return io->lanes;
  }

  // -- collectives (synchronous; caller provides an op thread) -------------

  // In-place ring allreduce over a contiguous buffer.
  void allreduce(void* data, size_t nbytes, DType dt, RedOp op) {
    ScatterView view(data, nbytes);
    IoPtr io = io_snapshot();
    allreduce_ring_io(io, view, dt, op, full_ring(io->world));
  }

  // In-place ring allreduce over MANY caller buffers treated as one
  // logical payload — the zero-copy multi-array path: frames are sent with
  // sendmsg straight from the callers' memory and received with recvmsg
  // straight into it; the payload is never assembled in a staging copy.
  // Every buffer must hold whole elements of `dt` (the Python binding
  // groups arrays by dtype), so chunk math never splits an element.
  void allreduce_iov(void* const* bufs, const uint64_t* lens, size_t n,
                     DType dt, RedOp op) {
    ScatterView view(bufs, lens, n);
    IoPtr io = io_snapshot();
    allreduce_ring_io(io, view, dt, op, full_ring(io->world));
  }

  // Ring allreduce over a RANK SUBSET (global ranks in ring order) — the
  // hierarchical leader ring.  Ring position replaces rank in the chunk
  // schedule; the full ring compiles to the identical legacy schedule
  // (position == rank), and the Python tier's `ring=` parameter speaks the
  // same frames, so mixed-tier leader rings interoperate.
  void allreduce_ring(void* data, size_t nbytes, DType dt, RedOp op,
                      const std::vector<int64_t>& ring) {
    ScatterView view(data, nbytes);
    allreduce_ring(view, dt, op, ring);
  }

  void allreduce_ring(ScatterView& view, DType dt, RedOp op,
                      const std::vector<int64_t>& ring) {
    allreduce_ring_io(io_snapshot(), view, dt, op, ring);
  }

  void allreduce_ring_io(IoPtr io, ScatterView& view, DType dt, RedOp op,
                         const std::vector<int64_t>& ring) {
    if (ring.size() <= 1) return;
    size_t esz = dtype_size(dt);
    auto deadline = deadline_in(timeout_s_);
    auto bounds = ring_bounds(view.size() / esz, ring.size());

    // shift -1 on BOTH phases: the Python tier's schedule (ring position p
    // ends the reduce phase owning chunk p, the conventional contract —
    // communicator._ring_reduce_scatter sends pos-step-1 / recvs
    // pos-step-2, then allgather sends pos-step / recvs pos-step-1).  The
    // round-1 build ran the textbook shift-0 schedule here: correct alone,
    // but chunk indices landed rotated by one against a Python peer — a
    // silent cross-tier corruption the constant-fill interop test never
    // saw (mixed-tier bit-identity tests now pin this).
    ring_reduce_phase(io, view, bounds, esz, dt, op, /*shift=*/-1, deadline,
                      ring, /*tag_base=*/0);
    ring_allgather_phase(io, view, bounds, esz, /*shift=*/-1, deadline, ring,
                         /*tag_base=*/0);
  }

  // reduce-scatter: `data` is reduced in place ring-wise; this rank's chunk
  // (chunk `rank` of ws near-equal chunks over the flattened elements) ends
  // up fully reduced and is copied into `out`.  Returns the chunk's bytes.
  size_t reduce_scatter(void* data, size_t nbytes, DType dt, RedOp op,
                        void* out, size_t out_cap) {
    IoPtr io = io_snapshot();
    const int64_t rank = io->rank, ws = io->world;
    size_t esz = dtype_size(dt);
    auto bounds = ring_bounds(nbytes / esz, static_cast<size_t>(ws));
    uint8_t* bytes = static_cast<uint8_t*>(data);
    size_t own_off = bounds[rank] * esz;
    size_t own_bytes = (bounds[rank + 1] - bounds[rank]) * esz;
    if (own_bytes > out_cap)
      throw CommError("reduce_scatter out buffer too small");
    if (ws > 1) {
      auto deadline = deadline_in(timeout_s_);
      ScatterView view(data, nbytes);
      // shift -1: rank ends owning chunk `rank` (conventional contract);
      // the explicit-API tag window keeps these frames clear of allreduce
      ring_reduce_phase(io, view, bounds, esz, dt, op, /*shift=*/-1, deadline,
                        full_ring(ws), kRingReduceTagBase);
    }
    std::memcpy(out, bytes + own_off, own_bytes);
    return own_bytes;
  }

  void broadcast(void* data, size_t nbytes, int64_t root) {
    IoPtr io = io_snapshot();
    if (io->world <= 1) return;
    auto deadline = deadline_in(timeout_s_);
    if (io->rank == root) {
      // concurrent fan-out to every peer (send-only multi_exchange)
      uint8_t* src = static_cast<uint8_t*>(data);
      multi_exchange(
          io, peers_snapshot(),
          [&](int64_t) { return std::make_pair(src, nbytes); },
          [&](int64_t) {
            return std::make_pair(static_cast<uint8_t*>(nullptr), size_t(0));
          },
          3000, deadline);
    } else {
      ScatterView view(data, nbytes);
      recv_striped(*io, peer_fds(root), root, 3000, view, 0, nbytes,
                   deadline);
    }
  }

  void send(const void* data, size_t nbytes, int64_t dst, uint64_t tag) {
    IoPtr io = io_snapshot();
    auto deadline = deadline_in(timeout_s_);
    std::vector<struct iovec> payload;
    if (nbytes)
      payload.push_back({const_cast<void*>(data), nbytes});
    send_framed_iov(*io, peer_fd(dst, io->lanes - 1), dst, tag,
                    std::move(payload), nbytes, deadline, io->lanes - 1);
  }

  // zero-copy: receive one frame directly into a caller buffer; returns
  // the payload size (must be <= cap)
  size_t recv_into(int64_t src, uint64_t tag, void* buf, size_t cap) {
    IoPtr io = io_snapshot();
    size_t p2p_lane = io->lanes - 1;
    auto deadline = deadline_in(timeout_s_);
    int fd = peer_fd(src, p2p_lane);
    uint64_t hdr[2];
    recv_loop(*io, fd, src, hdr, 16, deadline, p2p_lane);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(src));
    if (hdr[0] > cap) {
      // drain the payload so the stream stays frame-aligned, THEN fail
      std::vector<uint8_t> scratch(1 << 20);
      uint64_t remaining = hdr[0];
      while (remaining > 0) {
        size_t take = std::min<uint64_t>(remaining, scratch.size());
        recv_loop(*io, fd, src, scratch.data(), take, deadline, p2p_lane);
        remaining -= take;
      }
      throw CommError("recv_into buffer too small: payload " +
                      std::to_string(hdr[0]) + " > cap " + std::to_string(cap));
    }
    recv_loop(*io, fd, src, buf, hdr[0], deadline, p2p_lane);
    return hdr[0];
  }

  // receiver learns the size from the frame header
  std::vector<uint8_t> recv_dynamic(int64_t src, uint64_t tag) {
    IoPtr io = io_snapshot();
    size_t p2p_lane = io->lanes - 1;
    auto deadline = deadline_in(timeout_s_);
    int fd = peer_fd(src, p2p_lane);
    uint64_t hdr[2];
    recv_loop(*io, fd, src, hdr, 16, deadline, p2p_lane);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(src));
    std::vector<uint8_t> out(hdr[0]);
    recv_loop(*io, fd, src, out.data(), out.size(), deadline, p2p_lane);
    return out;
  }

  // symmetric alltoall of equal-size chunks; chunks laid out contiguously in
  // `data` (ws chunks of chunk_bytes); received into `out` by source rank.
  void alltoall(const void* data, void* out, size_t chunk_bytes, uint64_t tag) {
    IoPtr io = io_snapshot();
    const uint8_t* in = static_cast<const uint8_t*>(data);
    std::vector<const void*> ins(static_cast<size_t>(io->world));
    for (int64_t p = 0; p < io->world; ++p) ins[p] = in + p * chunk_bytes;
    alltoall_ptrs_io(io, ins.data(), out, chunk_bytes, tag);
  }

  // scatter-gather alltoall: one pointer per destination rank's chunk (the
  // chunks need not be contiguous with each other — no staging concat)
  void alltoall_ptrs(const void* const* ins, void* out, size_t chunk_bytes,
                     uint64_t tag) {
    alltoall_ptrs_io(io_snapshot(), ins, out, chunk_bytes, tag);
  }

  void alltoall_ptrs_io(IoPtr io, const void* const* ins, void* out,
                        size_t chunk_bytes, uint64_t tag) {
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + io->rank * chunk_bytes, ins[io->rank], chunk_bytes);
    auto deadline = deadline_in(timeout_s_);
    // pairwise exchange with every peer concurrently
    multi_exchange(
        io, peers_snapshot(),
        [&](int64_t p) {
          return std::make_pair(
              static_cast<const uint8_t*>(ins[p]), chunk_bytes);
        },
        [&](int64_t p) { return std::make_pair(o + p * chunk_bytes, chunk_bytes); },
        4000 + tag, deadline);
  }

  void allgather(const void* data, void* out, size_t chunk_bytes, uint64_t tag) {
    IoPtr io = io_snapshot();
    const uint8_t* in = static_cast<const uint8_t*>(data);
    uint8_t* o = static_cast<uint8_t*>(out);
    std::memcpy(o + io->rank * chunk_bytes, in, chunk_bytes);
    auto deadline = deadline_in(timeout_s_);
    multi_exchange(
        io, peers_snapshot(),
        [&](int64_t) { return std::make_pair(in, chunk_bytes); },
        [&](int64_t p) { return std::make_pair(o + p * chunk_bytes, chunk_bytes); },
        5000 + tag, deadline);
  }

  void barrier() {
    float token = 0.0f;
    allreduce(&token, sizeof(token), DT_F32, OP_SUM);
  }

 private:
  using TimePoint = std::chrono::steady_clock::time_point;
  static TimePoint now() { return std::chrono::steady_clock::now(); }
  TimePoint deadline_in(double seconds) const {
    return now() + std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                       std::chrono::duration<double>(seconds));
  }

  std::vector<int> peer_fds(int64_t peer) {
    std::lock_guard<std::mutex> lock(state_mu_);
    auto it = peers_.find(peer);
    if (it == peers_.end())
      throw CommError("no peer " + std::to_string(peer) +
                      (aborted_ ? " (communicator aborted)" : ""));
    return it->second;
  }

  int peer_fd(int64_t peer, size_t lane = 0) {
    std::lock_guard<std::mutex> lock(state_mu_);
    auto it = peers_.find(peer);
    if (it == peers_.end() || lane >= it->second.size())
      throw CommError("no peer " + std::to_string(peer) +
                      (aborted_ ? " (communicator aborted)" : ""));
    return it->second[lane];
  }

  std::shared_ptr<LanePool> pool_snapshot() {
    std::lock_guard<std::mutex> lock(state_mu_);
    if (!pool_) throw CommError("communicator not configured");
    return pool_;
  }

  void check_abort() const {
    if (aborted_) throw CommError("communicator aborted");
  }

  IoPtr io_snapshot() const {
    std::lock_guard<std::mutex> lock(state_mu_);
    return io_;
  }

  // --- scatter-gather framed IO with abort/deadline checks per quantum ----
  //
  // One frame = 16-byte header (payload nbytes, tag) + payload, where the
  // payload may be scattered across many caller buffers: sendmsg pushes
  // header + payload segments in one syscall/TCP segment (with TCP_NODELAY
  // a separate header send costs a segment and a wakeup per frame), and
  // recvmsg lands payload bytes straight in the callers' segments.

  void send_framed_iov(EpochIO& io, int fd, int64_t peer, uint64_t tag,
                       std::vector<struct iovec> payload, size_t nbytes,
                       TimePoint deadline, size_t lane) {
    io.gate();
    uint64_t hdr[2] = {nbytes, tag};
    payload.insert(payload.begin(), {hdr, sizeof(hdr)});
    IovCursor cursor(std::move(payload));
    struct iovec batch[kMaxIovSegs + 1];
    size_t hdr_left = sizeof(hdr);
    while (cursor.remaining() > 0) {
      check_abort();
      if (now() > deadline) throw CommError("send timed out");
      size_t budget = cursor.remaining();
      if (io.pacer && cursor.remaining() > hdr_left) {
        // the header rides free (16 bytes of framing noise vs the Python
        // tier's per-chunk accounting parity)
        size_t want =
            std::min(cursor.remaining() - hdr_left, size_t(1) << 20);
        size_t allowed = io.pacer->allow(want, static_cast<uint64_t>(fd));
        // coalesce dribbles: a cwnd-limited stream bucket refills a few
        // tens of KB per scheduling quantum, and pushing each dribble
        // costs a syscall + a wakeup PER LANE THREAD — on small hosts
        // that thrash (not the token rate) becomes the ceiling.  Below
        // the floor, nap briefly instead (tokens keep accruing while we
        // sleep; nothing is consumed).
        size_t floor =
            std::min({want, kPaceMinSendBytes, io.pacer->max_grant() / 2});
        if (allowed < floor) {
          io.stall(lane);
          std::this_thread::sleep_for(std::chrono::microseconds(200));
          continue;
        }
        budget = allowed + hdr_left;
      }
      int cnt = cursor.fill(batch, kMaxIovSegs + 1, budget);
      if (cnt == 0) break;
      struct msghdr msg {};
      msg.msg_iov = batch;
      msg.msg_iovlen = cnt;
      ssize_t sent = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
      if (sent < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
          io.stall(lane);
          continue;  // quantum expired: re-check abort/deadline
        }
        throw CommError("send failed to rank " + std::to_string(peer));
      }
      size_t s = static_cast<size_t>(sent);
      size_t hdr_part = std::min(s, hdr_left);
      hdr_left -= hdr_part;
      if (io.pacer) io.pacer->consume(s - hdr_part, static_cast<uint64_t>(fd));
      io.add_tx(lane, s - hdr_part);
      cursor.advance(s);
    }
  }

  void recv_loop_iov(EpochIO& io, int fd, int64_t peer, IovCursor& cursor,
                     TimePoint deadline, size_t lane) {
    struct iovec batch[kMaxIovSegs];
    while (cursor.remaining() > 0) {
      check_abort();
      if (now() > deadline) throw CommError("recv timed out");
      int cnt = cursor.fill(batch, kMaxIovSegs, cursor.remaining());
      struct msghdr msg {};
      msg.msg_iov = batch;
      msg.msg_iovlen = cnt;
      ssize_t got = ::recvmsg(fd, &msg, 0);
      if (got == 0)
        throw CommError("connection to rank " + std::to_string(peer) +
                        " closed");
      if (got < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;  // quantum expired: re-check abort/deadline
        throw CommError("recv failed from rank " + std::to_string(peer));
      }
      io.add_rx(lane, static_cast<size_t>(got));
      cursor.advance(static_cast<size_t>(got));
    }
  }

  // --- lane-striped framed IO ---------------------------------------------
  //
  // One logical frame split across the lane connections by lane_parts();
  // part 0 runs on the calling thread, the rest on the epoch's persistent
  // per-lane workers, so on cwnd-limited links the streams genuinely run
  // in parallel.  Sub-frame boundaries are 64-byte aligned, so the reduce
  // variant can fold each lane's range independently — every element still
  // sees exactly one reduction per step: results are bit-identical to a
  // single lane.

  template <typename PartFn>
  void run_lane_parts(int64_t peer, int dir,
                      const std::vector<std::pair<size_t, size_t>>& parts,
                      PartFn fn) {
    if (parts.size() == 1) {
      fn(0, parts[0].first, parts[0].second);
      return;
    }
    auto pool = pool_snapshot();
    auto latch = std::make_shared<OpLatch>();
    latch->add(parts.size() - 1);
    for (size_t i = 1; i < parts.size(); ++i) {
      size_t s = parts[i].first, e = parts[i].second;
      pool->submit(peer, i, dir, [&fn, i, s, e, latch] {
        std::string err;
        try {
          fn(i, s, e);
        } catch (const std::exception& ex) {
          err = ex.what();
        }
        latch->done(err);
      });
    }
    std::string err0;
    try {
      fn(0, parts[0].first, parts[0].second);
    } catch (const std::exception& ex) {
      err0 = ex.what();
    }
    std::string err = latch->wait_quiet();
    if (!err0.empty()) throw CommError(err0);
    if (!err.empty()) throw CommError(err);
  }

  // striped send of view[off, off+nbytes) to peer, synchronous
  void send_striped(EpochIO& io, const std::vector<int>& fds, int64_t peer,
                    uint64_t tag, const ScatterView& view, size_t off,
                    size_t nbytes, TimePoint deadline) {
    auto parts = io.lane_parts(nbytes);
    if (io.pacer && parts.size() > 1) {
      // paced striped sends multiplex every lane on ONE thread: under a
      // token bucket the wire, not the CPU, is the bottleneck, and a
      // round-robin writer (exactly the Python select loop's shape)
      // saturates all cwnd-capped streams without n napping threads
      // fighting the scheduler on small hosts
      send_striped_multiplexed(io, fds, peer, tag, view, off, parts,
                               deadline);
      return;
    }
    run_lane_parts(peer, LanePool::kTx, parts,
                   [&](size_t lane, size_t s, size_t e) {
                     send_framed_iov(io, fds[lane], peer, tag,
                                     view.slice(off + s, e - s), e - s,
                                     deadline, lane);
                   });
  }

  // one thread drives every lane's sub-frame of a striped send,
  // round-robining the pacer grants; wire bytes are identical to the
  // per-lane-thread path (same frames on the same lanes, interleaving is
  // invisible to per-connection TCP streams)
  void send_striped_multiplexed(
      EpochIO& io, const std::vector<int>& fds, int64_t peer, uint64_t tag,
      const ScatterView& view, size_t off,
      const std::vector<std::pair<size_t, size_t>>& parts,
      TimePoint deadline) {
    io.gate();  // one gate arms every lane, like the Python loop
    struct LaneTx {
      int fd = -1;
      size_t lane = 0;
      uint64_t hdr[2] = {0, 0};
      IovCursor cursor;
      size_t hdr_left = sizeof(hdr);
    };
    std::vector<std::unique_ptr<LaneTx>> lanes;
    for (size_t i = 0; i < parts.size(); ++i) {
      size_t s = parts[i].first, e = parts[i].second;
      auto lt = std::make_unique<LaneTx>();
      lt->fd = fds[i];
      lt->lane = i;
      lt->hdr[0] = e - s;
      lt->hdr[1] = tag;
      auto iov = view.slice(off + s, e - s);
      // the header iovec points at THIS LaneTx's hdr storage
      iov.insert(iov.begin(), {lt->hdr, sizeof(lt->hdr)});
      lt->cursor = IovCursor(std::move(iov));
      lanes.push_back(std::move(lt));
    }
    struct iovec batch[kMaxIovSegs + 1];
    size_t live = lanes.size();
    while (live > 0) {
      check_abort();
      if (now() > deadline) throw CommError("send timed out");
      bool progressed = false;
      for (auto& lt : lanes) {
        if (lt->cursor.remaining() == 0) continue;
        size_t remaining = lt->cursor.remaining();
        size_t payload_left = remaining - lt->hdr_left;
        size_t budget = remaining;
        if (payload_left > 0) {
          size_t want = std::min(payload_left, size_t(1) << 20);
          size_t allowed =
              io.pacer->allow(want, static_cast<uint64_t>(lt->fd));
          size_t floor =
              std::min({want, kPaceMinSendBytes, io.pacer->max_grant() / 2});
          if (allowed < floor) {
            io.stall(lt->lane);
            continue;  // this lane is token-blocked; try the next
          }
          budget = allowed + lt->hdr_left;
        }
        int cnt = lt->cursor.fill(batch, kMaxIovSegs + 1, budget);
        if (cnt == 0) continue;
        struct msghdr msg {};
        msg.msg_iov = batch;
        msg.msg_iovlen = cnt;
        ssize_t sent = ::sendmsg(lt->fd, &msg, MSG_NOSIGNAL);
        if (sent < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
            io.stall(lt->lane);
            continue;
          }
          throw CommError("send failed to rank " + std::to_string(peer));
        }
        size_t s2 = static_cast<size_t>(sent);
        size_t hdr_part = std::min(s2, lt->hdr_left);
        lt->hdr_left -= hdr_part;
        io.pacer->consume(s2 - hdr_part, static_cast<uint64_t>(lt->fd));
        io.add_tx(lt->lane, s2 - hdr_part);
        lt->cursor.advance(s2);
        progressed = true;
        if (lt->cursor.remaining() == 0) --live;
      }
      if (!progressed && live > 0)
        std::this_thread::sleep_for(std::chrono::microseconds(200));
    }
  }

  // striped send dispatched entirely onto the per-lane tx workers; the
  // returned latch completes when every part is on the wire (the ring's
  // duplex steps run send and recv concurrently without a thread spawn)
  std::shared_ptr<OpLatch> send_striped_async(
      IoPtr io, const std::vector<int>& fds, int64_t peer, uint64_t tag,
      const ScatterView& view, size_t off, size_t nbytes, TimePoint deadline) {
    auto pool = pool_snapshot();
    auto latch = std::make_shared<OpLatch>();
    auto parts = io->lane_parts(nbytes);
    if (io->pacer && parts.size() > 1) {
      // paced: one multiplexer task round-robins every lane (see
      // send_striped) instead of a napping worker per lane
      latch->add(1);
      pool->submit(peer, 0, LanePool::kTx,
                   [this, io, fds, peer, tag, &view, off, parts, deadline,
                    latch] {
                     std::string err;
                     try {
                       send_striped_multiplexed(*io, fds, peer, tag, view,
                                                off, parts, deadline);
                     } catch (const std::exception& ex) {
                       err = ex.what();
                     }
                     latch->done(err);
                   });
      return latch;
    }
    latch->add(parts.size());
    for (size_t i = 0; i < parts.size(); ++i) {
      size_t s = parts[i].first, e = parts[i].second;
      int fd = fds[i];
      pool->submit(peer, i, LanePool::kTx,
                   [this, io, fd, peer, tag, &view, off, s, e, deadline,
                    latch, i] {
                     std::string err;
                     try {
                       send_framed_iov(*io, fd, peer, tag,
                                       view.slice(off + s, e - s), e - s,
                                       deadline, i);
                     } catch (const std::exception& ex) {
                       err = ex.what();
                     }
                     latch->done(err);
                   });
    }
    return latch;
  }

  void recv_striped(EpochIO& io, const std::vector<int>& fds, int64_t peer,
                    uint64_t tag, ScatterView& view, size_t off,
                    size_t nbytes, TimePoint deadline) {
    run_lane_parts(peer, LanePool::kRx, io.lane_parts(nbytes),
                   [&](size_t lane, size_t s, size_t e) {
                     recv_framed_iov(io, fds[lane], peer, tag, view, off + s,
                                     e - s, deadline, lane);
                   });
  }

  void recv_striped_reduce(EpochIO& io, const std::vector<int>& fds,
                           int64_t peer, uint64_t tag, ScatterView& view,
                           size_t off, size_t nbytes, DType dt, RedOp op,
                           TimePoint deadline,
                           std::vector<std::vector<uint8_t>>& scratches) {
    auto parts = io.lane_parts(nbytes);
    // per-lane scratch from the caller's pool (grown once, reused across
    // ring steps): the quantum-pipelined reduce runs concurrently on every
    // lane over disjoint destination ranges
    if (scratches.size() < parts.size()) scratches.resize(parts.size());
    for (size_t i = 0; i < parts.size(); ++i) {
      size_t want =
          std::min<size_t>(parts[i].second - parts[i].first, size_t(4) << 20) +
          64;
      if (scratches[i].size() < want) scratches[i].resize(want);
    }
    run_lane_parts(peer, LanePool::kRx, parts,
                   [&](size_t lane, size_t s, size_t e) {
                     recv_framed_reduce(io, fds[lane], peer, tag, view,
                                        off + s, e - s,
                                        scratches[lane].data(), dt, op,
                                        deadline, lane);
                   });
  }

  // element bounds per ring chunk (first n%ws chunks one element longer)
  static std::vector<size_t> ring_bounds(size_t n, size_t ws) {
    std::vector<size_t> bounds(ws + 1, 0);
    size_t base = n / ws, extra = n % ws;
    for (size_t i = 0; i < ws; ++i)
      bounds[i + 1] = bounds[i] + base + (i < extra ? 1 : 0);
    return bounds;
  }

  static std::vector<int64_t> full_ring(int64_t ws) {
    std::vector<int64_t> ring(ws);
    for (int64_t i = 0; i < ws; ++i) ring[i] = i;
    return ring;
  }

  static int64_t ring_pos(const std::vector<int64_t>& ring, int64_t rank) {
    auto it = std::find(ring.begin(), ring.end(), rank);
    if (it == ring.end())
      throw CommError("rank " + std::to_string(rank) + " not in ring");
    return it - ring.begin();
  }

  // ring reduce phase: ws-1 duplex steps over `ring` (global ranks in ring
  // order; ws = ring.size()); with shift s, this rank's ring POSITION ends
  // up owning the fully-reduced chunk (pos + 1 + s) mod ws.  The (memory-
  // bound) reduction rides under the wire via quantum-pipelined recv; the
  // send leg runs on the per-lane tx workers, the recv leg on the calling
  // thread + rx workers.
  void ring_reduce_phase(IoPtr io, ScatterView& view,
                         const std::vector<size_t>& bounds, size_t esz,
                         DType dt, RedOp op, int64_t shift,
                         TimePoint deadline, const std::vector<int64_t>& ring,
                         uint64_t tag_base) {
    int64_t ws = static_cast<int64_t>(ring.size());
    int64_t pos = ring_pos(ring, io->rank);
    int64_t right = ring[(pos + 1) % ws];
    int64_t left = ring[(pos - 1 + ws) % ws];
    auto chunk_off = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return bounds[i] * esz;
    };
    auto chunk_bytes = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return (bounds[i + 1] - bounds[i]) * esz;
    };
    std::vector<int> right_fds = peer_fds(right);
    std::vector<int> left_fds = peer_fds(left);
    std::vector<std::vector<uint8_t>> scratches;  // grown once, reused/step
    for (int64_t step = 0; step < ws - 1; ++step) {
      int64_t send_idx = pos - step + shift;
      int64_t recv_idx = pos - step - 1 + shift;
      auto send_latch =
          send_striped_async(io, right_fds, right, tag_base + 1000 + step,
                             view, chunk_off(send_idx), chunk_bytes(send_idx),
                             deadline);
      try {
        recv_striped_reduce(*io, left_fds, left, tag_base + 1000 + step, view,
                            chunk_off(recv_idx), chunk_bytes(recv_idx), dt, op,
                            deadline, scratches);
      } catch (...) {
        send_latch->wait_quiet();
        throw;
      }
      send_latch->wait();
    }
  }

  // ring allgather phase: ws-1 duplex steps circulating the fully-reduced
  // chunks over `ring`; with shift s, this rank's ring position starts
  // owning chunk (pos + 1 + s) mod ws.
  void ring_allgather_phase(IoPtr io, ScatterView& view,
                            const std::vector<size_t>& bounds, size_t esz,
                            int64_t shift, TimePoint deadline,
                            const std::vector<int64_t>& ring,
                            uint64_t tag_base) {
    int64_t ws = static_cast<int64_t>(ring.size());
    int64_t pos = ring_pos(ring, io->rank);
    int64_t right = ring[(pos + 1) % ws];
    int64_t left = ring[(pos - 1 + ws) % ws];
    auto chunk_off = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return bounds[i] * esz;
    };
    auto chunk_bytes = [&](int64_t i) {
      i = ((i % ws) + ws) % ws;
      return (bounds[i + 1] - bounds[i]) * esz;
    };
    std::vector<int> right_fds = peer_fds(right);
    std::vector<int> left_fds = peer_fds(left);
    for (int64_t step = 0; step < ws - 1; ++step) {
      int64_t send_idx = pos + 1 + shift - step;
      int64_t recv_idx = pos + shift - step;
      auto send_latch =
          send_striped_async(io, right_fds, right, tag_base + 2000 + step,
                             view, chunk_off(send_idx), chunk_bytes(send_idx),
                             deadline);
      try {
        recv_striped(*io, left_fds, left, tag_base + 2000 + step, view,
                     chunk_off(recv_idx),
                     chunk_bytes(recv_idx), deadline);
      } catch (...) {
        send_latch->wait_quiet();
        throw;
      }
      send_latch->wait();
    }
  }

  // recv a frame in quanta, reducing each quantum into the view as it
  // arrives (TCP delivers in order, so progressive reduction needs only a
  // quantum-sized scratch and overlaps compute with the wire)
  void recv_framed_reduce(EpochIO& io, int fd, int64_t peer, uint64_t tag,
                          ScatterView& view, size_t dst_off, size_t nbytes,
                          uint8_t* scratch, DType dt, RedOp op,
                          TimePoint deadline, size_t lane) {
    static constexpr size_t kQuantum = size_t(4) << 20;
    uint64_t hdr[2];
    recv_loop(io, fd, peer, hdr, 16, deadline, lane, /*count=*/false);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(peer));
    if (hdr[0] != nbytes)
      throw CommError("size mismatch from rank " + std::to_string(peer));
    size_t esz = dtype_size(dt);
    size_t quantum = kQuantum - (kQuantum % (esz ? esz : 1));
    size_t off = 0;
    while (off < nbytes) {
      size_t take = std::min(quantum, nbytes - off);
      recv_loop(io, fd, peer, scratch, take, deadline, lane);
      view.reduce_in(dst_off + off, scratch, take, dt, op);
      off += take;
    }
  }

  // recv one frame straight into the view's segments (zero staging copy)
  void recv_framed_iov(EpochIO& io, int fd, int64_t peer, uint64_t tag,
                       ScatterView& view, size_t dst_off, size_t nbytes,
                       TimePoint deadline, size_t lane) {
    uint64_t hdr[2];
    recv_loop(io, fd, peer, hdr, 16, deadline, lane, /*count=*/false);
    if (hdr[1] != tag)
      throw CommError("tag mismatch from rank " + std::to_string(peer));
    if (hdr[0] != nbytes)
      throw CommError("size mismatch from rank " + std::to_string(peer));
    IovCursor cursor(view.slice(dst_off, nbytes));
    recv_loop_iov(io, fd, peer, cursor, deadline, lane);
  }

  void recv_loop(EpochIO& io, int fd, int64_t peer, void* buf, size_t n,
                 TimePoint deadline, size_t lane, bool count = true) {
    uint8_t* p = static_cast<uint8_t*>(buf);
    while (n > 0) {
      check_abort();
      if (now() > deadline) throw CommError("recv timed out");
      ssize_t got = ::recv(fd, p, n, 0);
      if (got == 0)
        throw CommError("connection to rank " + std::to_string(peer) + " closed");
      if (got < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
          continue;  // quantum expired: re-check abort/deadline
        throw CommError("recv failed from rank " + std::to_string(peer));
      }
      if (count) io.add_rx(lane, static_cast<size_t>(got));
      p += got;
      n -= static_cast<size_t>(got);
    }
  }

  // all-peers concurrent exchange (alltoall/allgather/broadcast fan-out):
  // one duplex leg per peer on the persistent lane workers (the (peer, 0)
  // tx/rx pair coordinates; parts >= 1 fan out to that peer's lane
  // workers), each leg lane-striped.
  template <typename SendFn, typename RecvFn>
  void multi_exchange(IoPtr io,
                      const std::map<int64_t, std::vector<int>>& peers,
                      SendFn send_for, RecvFn recv_for, uint64_t tag,
                      TimePoint deadline) {
    auto pool = pool_snapshot();
    auto latch = std::make_shared<OpLatch>();
    std::vector<std::function<void()>> legs;
    for (const auto& entry : peers) {
      // plain locals (not structured bindings): C++17 lambdas cannot
      // portably capture the latter
      int64_t peer = entry.first;
      std::vector<int> pfds = entry.second;
      auto send_pair = send_for(peer);
      auto recv_pair = recv_for(peer);
      const uint8_t* sb = send_pair.first;
      size_t sn = send_pair.second;
      uint8_t* rb = recv_pair.first;
      size_t rn = recv_pair.second;
      latch->add(1);
      pool->submit(peer, 0, LanePool::kTx,
                   [this, io, pfds, peer, tag, sb, sn, deadline, latch] {
                     std::string err;
                     try {
                       ScatterView sv(const_cast<uint8_t*>(sb), sn);
                       send_striped(*io, pfds, peer, tag, sv, 0, sn,
                                    deadline);
                     } catch (const std::exception& ex) {
                       err = ex.what();
                     }
                     latch->done(err);
                   });
      if (rb != nullptr) {
        latch->add(1);
        pool->submit(peer, 0, LanePool::kRx,
                     [this, io, pfds, peer, tag, rb, rn, deadline, latch] {
                       std::string err;
                       try {
                         ScatterView rv(rb, rn);
                         recv_striped(*io, pfds, peer, tag, rv, 0, rn,
                                      deadline);
                       } catch (const std::exception& ex) {
                         err = ex.what();
                       }
                       latch->done(err);
                     });
      }
    }
    latch->wait();
  }

  // epoch-scalar mirrors for the PUBLIC accessors (rank()/size()/lanes()/
  // stripe_floor()/lane_parts()): written only by configure()'s publish
  // step, read by the binding from foreign threads — atomics because those
  // reads race the publish.  Op bodies never touch these: they read the
  // EpochIO snapshot, whose rank/world/lanes are immutable per epoch, so a
  // superseded op can never mix two epochs' values inside one collective.
  std::atomic<double> timeout_s_;
  std::atomic<int64_t> rank_{0};
  std::atomic<int64_t> world_size_{1};
  std::atomic<size_t> lanes_{1};
  std::atomic<size_t> stripe_floor_{kMinStripeBytes};
  std::atomic<bool> aborted_{false};
  // guards peers_/graveyard_/pool_/io_ STRUCTURE only — never held across
  // IO; ops snapshot the fds/pool/io they need at entry (fds stay open
  // until destruction, so a snapshot can never dangle; superseded pools
  // and EpochIO instances park in shared_ptrs held by in-flight ops)
  mutable std::mutex state_mu_;
  std::map<int64_t, std::vector<int>> peers_;
  std::shared_ptr<LanePool> pool_;
  IoPtr io_;
  std::vector<int> graveyard_;
  // epochs ever published (abort() only records a flight event once a
  // real epoch existed — configure()'s supersede-abort at boot is noise)
  std::atomic<int64_t> flight_epochs_{0};
  // guards flight_/flight_seq_/flight_drained_
  std::mutex flight_mu_;
  std::array<FlightSlot, kFlightRingSlots> flight_;
  uint64_t flight_seq_ = 0;
  uint64_t flight_drained_ = 0;
};

}  // namespace tpuft
