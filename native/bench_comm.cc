// Standalone throughput benchmark for the native communicator (no Python):
//   ./bench_comm            — forks store + 2 ranks, 256MB p2p + ring
#include <sys/wait.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <vector>

#include "comm.h"
#include "store.h"

using namespace tpuft;

static void run_rank(const std::string& store_addr, int rank) {
  Communicator comm(60.0);
  comm.configure(store_addr + "/bench", rank, 2);
  const size_t N = 256ull << 20;
  std::vector<uint8_t> payload(N, 7);

  // p2p warm + timed
  if (rank == 0) {
    comm.send(payload.data(), N, 1, 1);
    auto t0 = std::chrono::steady_clock::now();
    comm.send(payload.data(), N, 1, 2);
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0).count();
    std::printf("send 256MB: %.3fs (%.2f GB/s)\n", dt, N / dt / 1e9);
  } else {
    comm.recv_dynamic(0, 1);
    auto t0 = std::chrono::steady_clock::now();
    auto data = comm.recv_dynamic(0, 2);
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0).count();
    std::printf("recv 256MB: %.3fs (%.2f GB/s)\n", dt, data.size() / dt / 1e9);
  }

  // ring allreduce 128MB f32
  std::vector<float> buf(32 << 20, 1.0f);
  comm.allreduce(buf.data(), buf.size() * 4, DT_F32, OP_SUM);  // warm
  auto t0 = std::chrono::steady_clock::now();
  comm.allreduce(buf.data(), buf.size() * 4, DT_F32, OP_SUM);
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - t0).count();
  std::printf("rank %d ring 128MB: %.3fs (%.2f GB/s effective)\n", rank, dt,
              buf.size() * 4.0 / dt / 1e9);
}

int main() {
  StoreServer store("127.0.0.1:0");
  std::string addr = "127.0.0.1:" + std::to_string(store.port());
  pid_t pid = fork();
  if (pid == 0) {
    run_rank(addr, 1);
    _exit(0);
  }
  run_rank(addr, 0);
  int status = 0;
  waitpid(pid, &status, 0);
  return 0;
}
