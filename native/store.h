// TCP KV store server — C++ twin of torchft_tpu/store.py StoreServer.
// Wait-for-key gets with server-honored deadlines; atomic integer add;
// prefix delete.  One detached thread per connection (control-plane scale).

#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>

#include "wire.h"

namespace tpuft {

class StoreServer {
 public:
  explicit StoreServer(const std::string& bind_addr) {
    listen_fd_ = listen_on(bind_addr, &port_);
    accept_thread_ = std::thread([this] { serve(); });
  }

  ~StoreServer() { shutdown(); }

  int port() const { return port_; }

  void shutdown() {
    bool expected = false;
    if (!shutdown_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    conns_.shutdown_all_and_wait();  // handlers must exit before we die
  }

 private:
  void serve() {
    while (!shutdown_) {
      int conn = ::accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) return;
      configure_socket(conn);
      conns_.add(conn);
      std::thread([this, conn] {
        handle(conn);
        conns_.remove(conn);
      }).detach();
    }
  }

  void handle(int conn) {
    try {
      while (true) {
        auto [type, body] = recv_frame(conn);
        Reader r(body.data(), body.size());
        switch (type) {
          case STORE_SET: {
            std::string key = r.str();
            std::string value = r.blob();
            {
              std::lock_guard<std::mutex> lock(mu_);
              data_[key] = value;
            }
            cv_.notify_all();
            send_frame(conn, STORE_OK, Writer{});
            break;
          }
          case STORE_GET: {
            std::string key = r.str();
            uint64_t timeout_ms = r.u64();
            auto deadline = std::chrono::steady_clock::now() +
                            std::chrono::milliseconds(timeout_ms);
            std::unique_lock<std::mutex> lock(mu_);
            bool ok = cv_.wait_until(lock, deadline, [&] {
              return shutdown_ || data_.count(key) > 0;
            });
            if (!ok || shutdown_ || data_.count(key) == 0) {
              lock.unlock();
              send_error(conn, ERR_TIMEOUT,
                         "store get timed out for '" + key + "'");
            } else {
              Writer w;
              w.blob(data_[key]);
              lock.unlock();
              send_frame(conn, STORE_OK, w);
            }
            break;
          }
          case STORE_ADD: {
            std::string key = r.str();
            int64_t delta = r.i64();
            int64_t result;
            bool bad = false;
            {
              std::lock_guard<std::mutex> lock(mu_);
              int64_t cur = 0;
              auto it = data_.find(key);
              if (it != data_.end()) {
                try {
                  cur = std::stoll(it->second);
                } catch (...) {
                  bad = true;
                }
              }
              if (!bad) {
                result = cur + delta;
                data_[key] = std::to_string(result);
              }
            }
            if (bad) {
              send_error(conn, ERR_INVALID, "add on non-integer key '" + key + "'");
            } else {
              cv_.notify_all();
              Writer w;
              w.i64(result);
              send_frame(conn, STORE_OK, w);
            }
            break;
          }
          case STORE_EXISTS: {
            std::string key = r.str();
            bool present;
            {
              std::lock_guard<std::mutex> lock(mu_);
              present = data_.count(key) > 0;
            }
            Writer w;
            w.boolean(present);
            send_frame(conn, STORE_OK, w);
            break;
          }
          case STORE_DELETE: {
            std::string prefix = r.str();
            int64_t removed = 0;
            {
              std::lock_guard<std::mutex> lock(mu_);
              for (auto it = data_.begin(); it != data_.end();) {
                if (it->first.rfind(prefix, 0) == 0) {
                  it = data_.erase(it);
                  ++removed;
                } else {
                  ++it;
                }
              }
            }
            Writer w;
            w.i64(removed);
            send_frame(conn, STORE_OK, w);
            break;
          }
          default:
            send_error(conn, ERR_INVALID, "bad store op");
        }
      }
    } catch (const std::exception&) {
      // connection closed or protocol error: drop the connection
    }
    ::close(conn);
  }

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  // guards data_
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> data_;
  ConnRegistry conns_;
};

// Minimal store client (used by the C++ communicator for rendezvous).
class StoreClient {
 public:
  StoreClient(const std::string& addr, double timeout_s)
      : addr_(addr), timeout_s_(timeout_s) {
    fd_ = dial(addr, timeout_s);
  }
  ~StoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  void set(const std::string& key, const std::string& value) {
    Writer w;
    w.str(key);
    w.blob(value);
    call(STORE_SET, w, timeout_s_);
  }

  std::string get(const std::string& key, double timeout_s) {
    Writer w;
    w.str(key);
    w.u64(static_cast<uint64_t>(timeout_s * 1000));
    auto body = call(STORE_GET, w, timeout_s);
    Reader r(body.data(), body.size());
    return r.blob();
  }

 private:
  std::vector<uint8_t> call(MsgType type, const Writer& w, double budget) {
    set_recv_timeout(fd_, budget + 5.0);
    send_frame(fd_, type, w);
    auto [resp, body] = recv_frame(fd_);
    if (resp == ERROR_FRAME) {
      Reader r(body.data(), body.size());
      ErrCode code = static_cast<ErrCode>(r.u8());
      throw WireError(code, r.str());
    }
    return body;
  }

  std::string addr_;
  double timeout_s_;
  int fd_ = -1;
};

}  // namespace tpuft
