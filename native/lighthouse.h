// Lighthouse quorum service — C++ twin of torchft_tpu/lighthouse.py, itself
// the behavioral twin of the reference Rust service (src/lighthouse.rs).
//
// Semantics (see the Python docstrings for the full derivation):
//  - quorum_compute: heartbeat freshness filter, fast-quorum when all
//    previous members are back, shrink_only restriction, min_replicas,
//    anti-split-brain strict majority, join-timeout straggler wait.
//  - tick loop bumping quorum_id on membership change / commit failures;
//    participants cleared after issuance.
//  - blocking quorum RPC honoring client deadlines; parked waiters that a
//    quorum excluded are re-registered atomically inside the tick.

#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "types.h"
#include "wire.h"

namespace tpuft {

using Clock = std::chrono::steady_clock;

struct LighthouseConfig {
  uint64_t min_replicas = 1;
  uint64_t join_timeout_ms = 100;
  uint64_t quorum_tick_ms = 100;
  uint64_t heartbeat_timeout_ms = 5000;
};

struct MemberDetails {
  Clock::time_point joined;
  QuorumMember member;
};

struct LighthouseState {
  std::map<std::string, MemberDetails> participants;
  std::map<std::string, Clock::time_point> heartbeats;
  bool has_prev = false;
  Quorum prev_quorum;
  int64_t quorum_id = 0;
};

// (quorum participants or empty, reason); `met` out-param signals validity.
inline std::vector<QuorumMember> quorum_compute(
    Clock::time_point now, const LighthouseState& state,
    const LighthouseConfig& cfg, bool* met, std::string* reason) {
  const auto hb_timeout = std::chrono::milliseconds(cfg.heartbeat_timeout_ms);
  std::set<std::string> healthy_replicas;
  for (const auto& [rid, ts] : state.heartbeats)
    if (now - ts < hb_timeout) healthy_replicas.insert(rid);

  std::map<std::string, const MemberDetails*> healthy_participants;
  for (const auto& [rid, details] : state.participants)
    if (healthy_replicas.count(rid)) healthy_participants[rid] = &details;

  std::vector<QuorumMember> candidates;
  bool shrink_only = false;
  for (const auto& [rid, details] : healthy_participants) {
    candidates.push_back(details->member);
    shrink_only = shrink_only || details->member.shrink_only;
  }
  std::sort(candidates.begin(), candidates.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  char meta[160];
  std::snprintf(meta, sizeof(meta),
                "[%zu/%zu participants healthy][%zu heartbeating][shrink_only=%s]",
                healthy_participants.size(), state.participants.size(),
                healthy_replicas.size(), shrink_only ? "True" : "False");

  if (state.has_prev) {
    std::set<std::string> prev_ids;
    for (const auto& p : state.prev_quorum.participants)
      prev_ids.insert(p.replica_id);
    if (shrink_only) {
      std::vector<QuorumMember> filtered;
      for (const auto& m : candidates)
        if (prev_ids.count(m.replica_id)) filtered.push_back(m);
      candidates = std::move(filtered);
    }
    bool fast = true;
    for (const auto& rid : prev_ids)
      if (!healthy_participants.count(rid)) fast = false;
    if (fast) {
      *met = true;
      *reason = std::string("Fast quorum found! ") + meta;
      return candidates;
    }
  }

  if (healthy_participants.size() < cfg.min_replicas) {
    *met = false;
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need min_replicas " +
              std::to_string(cfg.min_replicas) + " " + meta;
    return {};
  }

  if (healthy_participants.size() <= healthy_replicas.size() / 2) {
    *met = false;
    *reason = "New quorum not ready, only have " +
              std::to_string(healthy_participants.size()) +
              " participants, need at least half of " +
              std::to_string(healthy_replicas.size()) + " healthy workers " +
              meta;
    return {};
  }

  bool all_joined = healthy_participants.size() == healthy_replicas.size();
  Clock::time_point first_joined = now;
  for (const auto& [rid, details] : healthy_participants)
    first_joined = std::min(first_joined, details->joined);
  if (!all_joined &&
      now - first_joined < std::chrono::milliseconds(cfg.join_timeout_ms)) {
    *met = false;
    *reason = std::string("Valid quorum waiting for stragglers due to join timeout ") + meta;
    return {};
  }

  *met = true;
  *reason = std::string("Valid quorum found ") + meta;
  return candidates;
}

class LighthouseServer {
 public:
  LighthouseServer(const std::string& bind_addr, const LighthouseConfig& cfg)
      : cfg_(cfg) {
    listen_fd_ = listen_on(bind_addr, &port_);
    accept_thread_ = std::thread([this] { serve(); });
    tick_thread_ = std::thread([this] { run_ticks(); });
  }

  ~LighthouseServer() { shutdown(); }

  int port() const { return port_; }

  void shutdown() {
    bool expected = false;
    if (!shutdown_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (tick_thread_.joinable()) tick_thread_.join();
    conns_.shutdown_all_and_wait();  // handlers must exit before we die
  }

 private:
  void serve() {
    while (!shutdown_) {
      int conn = ::accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) return;
      configure_socket(conn);
      conns_.add(conn);
      std::thread([this, conn] {
        handle(conn);
        conns_.remove(conn);
      }).detach();
    }
  }

  void run_ticks() {
    while (!shutdown_) {
      std::this_thread::sleep_for(std::chrono::milliseconds(cfg_.quorum_tick_ms));
      std::unique_lock<std::mutex> lock(mu_);
      tick_locked();
    }
  }

  void register_member_locked(const QuorumMember& m) {
    auto now = Clock::now();
    state_.heartbeats[m.replica_id] = now;  // implicit heartbeat
    state_.participants[m.replica_id] = MemberDetails{now, m};
  }

  void tick_locked() {
    bool met = false;
    std::string reason;
    auto participants = quorum_compute(Clock::now(), state_, cfg_, &met, &reason);
    if (!met) return;

    bool commit_failures = false;
    for (const auto& p : participants)
      if (p.commit_failures > 0) commit_failures = true;

    auto changed = [&] {
      if (!state_.has_prev) return true;
      const auto& prev = state_.prev_quorum.participants;
      if (prev.size() != participants.size()) return true;
      for (size_t i = 0; i < prev.size(); ++i)
        if (prev[i].replica_id != participants[i].replica_id) return true;
      return false;
    }();
    if (changed || commit_failures) state_.quorum_id += 1;

    Quorum q;
    q.quorum_id = state_.quorum_id;
    q.participants = participants;
    q.created =
        std::chrono::duration<double>(
            std::chrono::system_clock::now().time_since_epoch())
            .count();
    state_.prev_quorum = q;
    state_.has_prev = true;
    state_.participants.clear();

    // atomically re-register parked waiters the quorum excluded
    std::set<std::string> included;
    for (const auto& p : participants) included.insert(p.replica_id);
    for (const auto& [token, member] : parked_)
      if (!included.count(member.replica_id)) register_member_locked(member);

    generation_ += 1;
    cv_.notify_all();
  }

  void handle(int conn) {
    try {
      // protocol sniff: HTTP (dashboard) vs framed RPC on one port
      char head[4] = {0};
      ssize_t peeked = ::recv(conn, head, 4, MSG_PEEK);
      if (peeked >= 3 && (std::memcmp(head, "GET", 3) == 0 ||
                          std::memcmp(head, "POS", 3) == 0 ||
                          std::memcmp(head, "HEA", 3) == 0)) {
        handle_http(conn);
        ::close(conn);
        return;
      }
      while (true) {
        auto [type, body] = recv_frame(conn);
        Reader r(body.data(), body.size());
        switch (type) {
          case LH_HEARTBEAT_REQ: {
            std::string rid = r.str();
            {
              std::lock_guard<std::mutex> lock(mu_);
              state_.heartbeats[rid] = Clock::now();
            }
            send_frame(conn, LH_HEARTBEAT_RESP, Writer{});
            break;
          }
          case LH_QUORUM_REQ:
            handle_quorum(conn, r);
            break;
          case LH_STATUS_REQ: {
            Writer w;
            w.str(status_json());
            send_frame(conn, LH_STATUS_RESP, w);
            break;
          }
          default:
            send_error(conn, ERR_INVALID, "bad lighthouse op");
        }
      }
    } catch (const std::exception&) {
    }
    ::close(conn);
  }

  void handle_quorum(int conn, Reader& r) {
    QuorumMember requester = QuorumMember::decode(r);
    uint64_t timeout_ms = r.u64();
    auto deadline = Clock::now() + std::chrono::milliseconds(timeout_ms);

    Quorum result;
    bool failed = false;
    ErrCode fail_code = ERR_TIMEOUT;
    std::string fail_msg;
    uint64_t token = next_token_++;
    {
      std::unique_lock<std::mutex> lock(mu_);
      register_member_locked(requester);
      parked_[token] = requester;
      uint64_t gen = generation_;
      tick_locked();  // proactive tick
      while (true) {
        if (generation_ > gen) {
          gen = generation_;
          bool in_quorum = false;
          for (const auto& p : state_.prev_quorum.participants)
            if (p.replica_id == requester.replica_id) in_quorum = true;
          if (in_quorum) {
            result = state_.prev_quorum;
            break;
          }
          // excluded; tick_locked already re-registered us — keep waiting
        }
        if (Clock::now() >= deadline || shutdown_) {
          failed = true;
          fail_code = shutdown_ ? ERR_SHUTDOWN : ERR_TIMEOUT;
          fail_msg = "quorum request for '" + requester.replica_id + "' " +
                     (shutdown_ ? "aborted by shutdown" : "timed out");
          break;
        }
        cv_.wait_until(
            lock, std::min(deadline, Clock::now() + std::chrono::milliseconds(100)));
      }
      parked_.erase(token);
    }

    // socket IO outside the server lock
    if (failed) {
      send_error(conn, fail_code, fail_msg);
      return;
    }
    Writer w;
    result.encode(w);
    send_frame(conn, LH_QUORUM_RESP, w);
  }

  void handle_http(int conn) {
    set_recv_timeout(conn, 5.0);
    std::string req;
    char buf[2048];
    while (req.find("\r\n\r\n") == std::string::npos) {
      ssize_t got = ::recv(conn, buf, sizeof(buf), 0);
      if (got <= 0) return;
      req.append(buf, static_cast<size_t>(got));
      if (req.size() > 1 << 20) return;
    }
    std::string path = "/";
    auto sp1 = req.find(' ');
    if (sp1 != std::string::npos) {
      auto sp2 = req.find(' ', sp1 + 1);
      if (sp2 != std::string::npos) path = req.substr(sp1 + 1, sp2 - sp1 - 1);
    }

    std::string body;
    std::string ctype = "application/json";
    std::string status = "200 OK";
    const std::string kill_prefix = "/replica/";
    const std::string kill_suffix = "/kill";
    if (path.rfind(kill_prefix, 0) == 0 &&
        path.size() > kill_prefix.size() + kill_suffix.size() &&
        path.compare(path.size() - kill_suffix.size(), kill_suffix.size(),
                     kill_suffix) == 0) {
      std::string rid = path.substr(
          kill_prefix.size(),
          path.size() - kill_prefix.size() - kill_suffix.size());
      bool ok = kill_replica(rid);
      body = std::string("{\"ok\": ") + (ok ? "true" : "false") + "}";
      if (!ok) status = "404 Not Found";
    } else if (path == "/status.json" || path == "/status" || path == "/") {
      body = status_json();
    } else {
      status = "404 Not Found";
      body = "{\"error\": \"unknown path\"}";
    }
    std::string resp = "HTTP/1.1 " + status +
                       "\r\nContent-Type: " + ctype +
                       "\r\nContent-Length: " + std::to_string(body.size()) +
                       "\r\nConnection: close\r\n\r\n" + body;
    send_all(conn, resp.data(), resp.size());
  }

  bool kill_replica(const std::string& rid) {
    std::string addr;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (!state_.has_prev) return false;
      for (const auto& m : state_.prev_quorum.participants)
        if (m.replica_id == rid) addr = m.address;
    }
    if (addr.empty()) return false;
    try {
      int fd = dial(addr, 10.0);
      Writer w;
      w.str("killed from dashboard");
      send_frame(fd, MGR_KILL_REQ, w);
      ::close(fd);
      return true;
    } catch (const std::exception&) {
      return false;
    }
  }

  std::string status_json() {
    std::lock_guard<std::mutex> lock(mu_);
    std::string parts = "[";
    if (state_.has_prev) {
      bool first = true;
      for (const auto& m : state_.prev_quorum.participants) {
        if (!first) parts += ", ";
        first = false;
        parts += "{\"replica_id\": \"" + m.replica_id +
                 "\", \"address\": \"" + m.address +
                 "\", \"store_address\": \"" + m.store_address +
                 "\", \"step\": " + std::to_string(m.step) +
                 ", \"world_size\": " + std::to_string(m.world_size) + "}";
      }
    }
    parts += "]";
    std::string out = "{\"quorum_id\": " + std::to_string(state_.quorum_id) +
                      ", \"num_participants\": " +
                      (state_.has_prev
                           ? std::to_string(state_.prev_quorum.participants.size())
                           : "-1") +
                      ", \"participants\": " + parts +
                      ", \"impl\": \"cpp\"}";
    return out;
  }

  LighthouseConfig cfg_;
  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;

  // guards state_/parked_/generation_
  std::mutex mu_;
  std::condition_variable cv_;
  LighthouseState state_;
  std::map<uint64_t, QuorumMember> parked_;
  uint64_t generation_ = 0;
  std::atomic<uint64_t> next_token_{0};
  ConnRegistry conns_;
};

}  // namespace tpuft
