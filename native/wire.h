// Framed binary wire protocol — C++ twin of torchft_tpu/wire.py.
//
// The reference implements its control plane as tonic/gRPC Rust services
// (src/lighthouse.rs, src/manager.rs); torchft_tpu uses this dependency-free
// framed protocol so the same servers exist in both Python (development) and
// C++ (production runtime), interchangeable behind the Python clients.
//
// Frame: u32 payload_len (LE) | u8 msg_type | body. Primitives little-endian;
// strings/bytes are u32 length + raw bytes.

#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <set>
#include <thread>
#include <optional>
#include <stdexcept>
#include <string>
#include <vector>

namespace tpuft {

enum MsgType : uint8_t {
  STORE_SET = 0x01,
  STORE_GET = 0x02,
  STORE_ADD = 0x03,
  STORE_EXISTS = 0x04,
  STORE_DELETE = 0x05,
  STORE_OK = 0x0E,
  LH_QUORUM_REQ = 0x10,
  LH_QUORUM_RESP = 0x11,
  LH_HEARTBEAT_REQ = 0x12,
  LH_HEARTBEAT_RESP = 0x13,
  LH_STATUS_REQ = 0x14,
  LH_STATUS_RESP = 0x15,
  MGR_QUORUM_REQ = 0x20,
  MGR_QUORUM_RESP = 0x21,
  MGR_CKPT_META_REQ = 0x22,
  MGR_CKPT_META_RESP = 0x23,
  MGR_SHOULD_COMMIT_REQ = 0x24,
  MGR_SHOULD_COMMIT_RESP = 0x25,
  MGR_KILL_REQ = 0x26,
  MGR_KILL_RESP = 0x27,
  ERROR_FRAME = 0x7F,
};

enum ErrCode : uint8_t {
  ERR_UNKNOWN = 0,
  ERR_TIMEOUT = 1,
  ERR_NOT_FOUND = 2,
  ERR_INVALID = 3,
  ERR_SHUTDOWN = 4,
};

constexpr uint64_t kMaxFrameBytes = 64ull * 1024 * 1024;

struct WireError : std::runtime_error {
  ErrCode code;
  explicit WireError(ErrCode c, const std::string& msg)
      : std::runtime_error(msg), code(c) {}
};

class Writer {
 public:
  void u8(uint8_t v) { buf_.push_back(v); }
  void u32(uint32_t v) { append(&v, 4); }
  void u64(uint64_t v) { append(&v, 8); }
  void i64(int64_t v) { append(&v, 8); }
  void f64(double v) { append(&v, 8); }
  void boolean(bool v) { u8(v ? 1 : 0); }
  void str(const std::string& s) {
    u32(static_cast<uint32_t>(s.size()));
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  void blob(const std::string& s) { str(s); }
  void opt_i64(const std::optional<int64_t>& v) {
    if (v.has_value()) {
      u8(1);
      i64(*v);
    } else {
      u8(0);
    }
  }
  const std::vector<uint8_t>& data() const { return buf_; }

 private:
  void append(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);  // little-endian hosts only
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t n) : data_(data), n_(n) {}

  uint8_t u8() { return *take(1); }
  uint32_t u32() { return load<uint32_t>(); }
  uint64_t u64() { return load<uint64_t>(); }
  int64_t i64() { return load<int64_t>(); }
  double f64() { return load<double>(); }
  bool boolean() { return u8() != 0; }
  std::string str() {
    uint32_t len = u32();
    const uint8_t* p = take(len);
    return std::string(reinterpret_cast<const char*>(p), len);
  }
  std::string blob() { return str(); }
  std::optional<int64_t> opt_i64() {
    if (u8() == 0) return std::nullopt;
    return i64();
  }

 private:
  template <typename T>
  T load() {
    T v;
    std::memcpy(&v, take(sizeof(T)), sizeof(T));
    return v;
  }
  const uint8_t* take(size_t n) {
    if (off_ + n > n_) throw WireError(ERR_INVALID, "truncated frame");
    const uint8_t* p = data_ + off_;
    off_ += n;
    return p;
  }
  const uint8_t* data_;
  size_t n_;
  size_t off_ = 0;
};

// ---------------------------------------------------------------------------
// socket helpers
// ---------------------------------------------------------------------------

inline void send_all(int fd, const void* data, size_t n) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  while (n > 0) {
    ssize_t sent = ::send(fd, p, n, MSG_NOSIGNAL);
    if (sent <= 0) throw WireError(ERR_UNKNOWN, "send failed");
    p += sent;
    n -= static_cast<size_t>(sent);
  }
}

inline void recv_exact(int fd, void* data, size_t n) {
  uint8_t* p = static_cast<uint8_t*>(data);
  while (n > 0) {
    ssize_t got = ::recv(fd, p, n, 0);
    if (got == 0) throw WireError(ERR_UNKNOWN, "connection closed");
    if (got < 0) throw WireError(ERR_UNKNOWN, "recv failed");
    p += got;
    n -= static_cast<size_t>(got);
  }
}

inline void send_frame(int fd, MsgType type, const std::vector<uint8_t>& body) {
  uint32_t len = static_cast<uint32_t>(body.size() + 1);
  std::vector<uint8_t> frame;
  frame.reserve(5 + body.size());
  frame.insert(frame.end(), reinterpret_cast<uint8_t*>(&len),
               reinterpret_cast<uint8_t*>(&len) + 4);
  frame.push_back(type);
  frame.insert(frame.end(), body.begin(), body.end());
  send_all(fd, frame.data(), frame.size());
}

inline void send_frame(int fd, MsgType type, const Writer& w) {
  send_frame(fd, type, w.data());
}

inline void send_error(int fd, ErrCode code, const std::string& msg) {
  Writer w;
  w.u8(code);
  w.str(msg);
  send_frame(fd, ERROR_FRAME, w);
}

// returns (msg_type, body bytes)
inline std::pair<uint8_t, std::vector<uint8_t>> recv_frame(int fd) {
  uint32_t len;
  recv_exact(fd, &len, 4);
  if (len < 1 || len > kMaxFrameBytes)
    throw WireError(ERR_INVALID, "bad frame length");
  std::vector<uint8_t> body(len);
  recv_exact(fd, body.data(), len);
  uint8_t type = body[0];
  body.erase(body.begin());
  return {type, std::move(body)};
}

inline void configure_socket(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  ::setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
}

// bind a listening TCP socket on host:port (port 0 = ephemeral); returns fd
inline int listen_on(const std::string& bind_addr, int* out_port) {
  auto colon = bind_addr.rfind(':');
  std::string host = bind_addr.substr(0, colon);
  int port = std::stoi(bind_addr.substr(colon + 1));
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) throw WireError(ERR_UNKNOWN, "socket() failed");
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (host == "0.0.0.0" || host.empty()) {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    ::close(fd);
    throw WireError(ERR_INVALID, "bad bind host " + host);
  }
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    throw WireError(ERR_UNKNOWN, "bind failed for " + bind_addr);
  }
  if (::listen(fd, 512) != 0) {
    ::close(fd);
    throw WireError(ERR_UNKNOWN, "listen failed");
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  *out_port = ntohs(addr.sin_port);
  return fd;
}

// dial host:port with a connect timeout (seconds)
inline int dial(const std::string& addr, double timeout_s) {
  auto colon = addr.rfind(':');
  std::string host = addr.substr(0, colon);
  std::string port = addr.substr(colon + 1);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  if (::getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
    throw WireError(ERR_UNKNOWN, "getaddrinfo failed for " + addr);
  int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    ::freeaddrinfo(res);
    throw WireError(ERR_UNKNOWN, "socket() failed");
  }
  timeval tv{};
  tv.tv_sec = static_cast<long>(timeout_s);
  tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  if (::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    ::freeaddrinfo(res);
    ::close(fd);
    throw WireError(ERR_UNKNOWN, "connect failed to " + addr);
  }
  ::freeaddrinfo(res);
  configure_socket(fd);
  return fd;
}

inline void set_recv_timeout(int fd, double timeout_s) {
  timeval tv{};
  if (timeout_s > 0) {
    tv.tv_sec = static_cast<long>(timeout_s);
    tv.tv_usec = static_cast<long>((timeout_s - tv.tv_sec) * 1e6);
  }
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

// Tracks live connection handlers so a server can force-close their sockets
// and wait for every handler to exit before its state is destroyed.
class ConnRegistry {
 public:
  void add(int fd) {
    std::lock_guard<std::mutex> lock(mu_);
    fds_.insert(fd);
    ++active_;
  }
  void remove(int fd) {
    std::lock_guard<std::mutex> lock(mu_);
    fds_.erase(fd);
    --active_;
  }
  // close all handler sockets (unblocks their recv) and wait for exit
  void shutdown_all_and_wait() {
    {
      std::lock_guard<std::mutex> lock(mu_);
      for (int fd : fds_) ::shutdown(fd, SHUT_RDWR);
    }
    for (int i = 0; i < 500 && active_.load() > 0; ++i)
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
  }

 private:
  // guards fds_ (the counter below is atomic: the waiter polls it lock-free)
  std::mutex mu_;
  std::set<int> fds_;
  std::atomic<int> active_{0};
};

}  // namespace tpuft
