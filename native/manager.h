// Manager sidecar — C++ twin of torchft_tpu/manager_server.py (reference:
// src/manager.rs): intra-group quorum barrier → lighthouse forward with
// retries, deterministic recovery assignment, should_commit AND-barrier,
// checkpoint metadata registry, kill RPC, lighthouse heartbeat loop.

#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <map>
#include <mutex>
#include <optional>
#include <set>
#include <string>
#include <thread>
#include <vector>

#include "types.h"
#include "wire.h"

namespace tpuft {

inline ManagerQuorumResult compute_quorum_results(
    const std::string& replica_id, int64_t group_rank, const Quorum& quorum,
    bool init_sync) {
  std::vector<QuorumMember> participants = quorum.participants;
  std::sort(participants.begin(), participants.end(),
            [](const QuorumMember& a, const QuorumMember& b) {
              return a.replica_id < b.replica_id;
            });

  int64_t replica_rank = -1;
  for (size_t i = 0; i < participants.size(); ++i)
    if (participants[i].replica_id == replica_id)
      replica_rank = static_cast<int64_t>(i);
  if (replica_rank < 0)
    throw WireError(ERR_NOT_FOUND,
                    "replica " + replica_id + " not participating in returned quorum");

  int64_t max_step = participants[0].step;
  for (const auto& p : participants) max_step = std::max(max_step, p.step);
  std::vector<size_t> max_idx;
  for (size_t i = 0; i < participants.size(); ++i)
    if (participants[i].step == max_step) max_idx.push_back(i);

  std::optional<int64_t> max_replica_rank;
  for (size_t j = 0; j < max_idx.size(); ++j)
    if (participants[max_idx[j]].replica_id == replica_id)
      max_replica_rank = static_cast<int64_t>(j);

  const QuorumMember& primary =
      participants[max_idx[static_cast<size_t>(group_rank) % max_idx.size()]];

  bool force_recover = init_sync && max_step == 0;
  std::vector<size_t> recover_dst;
  for (size_t i = 0; i < participants.size(); ++i) {
    const auto& p = participants[i];
    if (p.step != max_step ||
        (force_recover && primary.replica_id != p.replica_id))
      recover_dst.push_back(i);
  }
  std::set<size_t> dst_set(recover_dst.begin(), recover_dst.end());
  std::vector<size_t> up_to_date;
  for (size_t i = 0; i < participants.size(); ++i)
    if (!dst_set.count(i)) up_to_date.push_back(i);

  std::map<size_t, std::vector<int64_t>> assignments;
  std::optional<int64_t> recover_src;
  for (size_t i = 0; i < recover_dst.size(); ++i) {
    size_t src =
        up_to_date[(i + static_cast<size_t>(group_rank)) % up_to_date.size()];
    assignments[src].push_back(static_cast<int64_t>(recover_dst[i]));
    if (static_cast<int64_t>(recover_dst[i]) == replica_rank)
      recover_src = static_cast<int64_t>(src);
  }

  ManagerQuorumResult out;
  out.quorum_id = quorum.quorum_id;
  out.replica_rank = replica_rank;
  out.replica_world_size = static_cast<int64_t>(participants.size());
  out.recover_src_replica_rank = recover_src;
  out.recover_src_manager_address =
      recover_src ? participants[static_cast<size_t>(*recover_src)].address : "";
  if (assignments.count(static_cast<size_t>(replica_rank)))
    out.recover_dst_replica_ranks = assignments[static_cast<size_t>(replica_rank)];
  out.store_address = primary.store_address;
  out.max_step = max_step;
  out.max_replica_rank = max_replica_rank;
  out.max_world_size = static_cast<int64_t>(max_idx.size());
  out.heal = recover_src.has_value();
  out.commit_failures = 0;
  for (const auto& p : participants) {
    out.commit_failures = std::max(out.commit_failures, p.commit_failures);
    out.replica_ids.push_back(p.replica_id);
  }
  return out;
}

class ManagerServer {
 public:
  ManagerServer(std::string replica_id, std::string lighthouse_addr,
                std::string hostname, const std::string& bind_addr,
                std::string store_addr, uint64_t world_size,
                double heartbeat_interval_s, double connect_timeout_s,
                int64_t quorum_retries)
      : replica_id_(std::move(replica_id)),
        lighthouse_addr_(std::move(lighthouse_addr)),
        hostname_(std::move(hostname)),
        store_addr_(std::move(store_addr)),
        world_size_(world_size),
        heartbeat_interval_s_(heartbeat_interval_s),
        connect_timeout_s_(connect_timeout_s),
        quorum_retries_(quorum_retries) {
    listen_fd_ = listen_on(bind_addr, &port_);
    accept_thread_ = std::thread([this] { serve(); });
    heartbeat_thread_ = std::thread([this] { run_heartbeat(); });
  }

  ~ManagerServer() { shutdown(); }

  int port() const { return port_; }
  std::string address() const {
    return hostname_ + ":" + std::to_string(port_);
  }

  void shutdown() {
    bool expected = false;
    if (!shutdown_.compare_exchange_strong(expected, true)) return;
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
    conns_.shutdown_all_and_wait();  // handlers must exit before we die
  }

 private:
  void serve() {
    while (!shutdown_) {
      int conn = ::accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) return;
      configure_socket(conn);
      conns_.add(conn);
      std::thread([this, conn] {
        handle(conn);
        conns_.remove(conn);
      }).detach();
    }
  }

  void run_heartbeat() {
    int fd = -1;
    while (!shutdown_) {
      try {
        if (fd < 0) fd = dial(lighthouse_addr_, connect_timeout_s_);
        Writer w;
        w.str(replica_id_);
        set_recv_timeout(fd, 5.0);
        send_frame(fd, LH_HEARTBEAT_REQ, w);
        auto [type, body] = recv_frame(fd);
        (void)type;
        (void)body;
      } catch (const std::exception&) {
        if (fd >= 0) ::close(fd);
        fd = -1;
      }
      std::this_thread::sleep_for(
          std::chrono::duration<double>(heartbeat_interval_s_));
    }
    if (fd >= 0) ::close(fd);
  }

  void handle(int conn) {
    try {
      while (true) {
        auto [type, body] = recv_frame(conn);
        Reader r(body.data(), body.size());
        switch (type) {
          case MGR_QUORUM_REQ:
            handle_quorum(conn, r);
            break;
          case MGR_CKPT_META_REQ: {
            int64_t rank = r.i64();
            std::optional<std::string> meta;
            {
              std::lock_guard<std::mutex> lock(mu_);
              auto it = checkpoint_metadata_.find(rank);
              if (it != checkpoint_metadata_.end()) meta = it->second;
            }
            if (!meta) {
              send_error(conn, ERR_INVALID, "rank not found");
            } else {
              Writer w;
              w.str(*meta);
              send_frame(conn, MGR_CKPT_META_RESP, w);
            }
            break;
          }
          case MGR_SHOULD_COMMIT_REQ:
            handle_should_commit(conn, r);
            break;
          case MGR_KILL_REQ: {
            send_frame(conn, MGR_KILL_RESP, Writer{});
            std::_Exit(1);
          }
          default:
            send_error(conn, ERR_INVALID, "bad manager op");
        }
      }
    } catch (const std::exception&) {
    }
    ::close(conn);
  }

  void handle_quorum(int conn, Reader& r) {
    int64_t group_rank = r.i64();
    int64_t step = r.i64();
    std::string checkpoint_metadata = r.str();
    bool shrink_only = r.boolean();
    bool init_sync = r.boolean();
    int64_t commit_failures = r.i64();
    uint64_t timeout_ms = r.u64();
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);

    Quorum quorum;
    bool failed = false;
    ErrCode fail_code = ERR_TIMEOUT;
    std::string fail_msg;
    {
      std::unique_lock<std::mutex> lock(mu_);
      checkpoint_metadata_[group_rank] = checkpoint_metadata;
      QuorumMember member;
      member.replica_id = replica_id_;
      member.address = address();
      member.store_address = store_addr_;
      member.step = step;
      member.world_size = world_size_;
      member.shrink_only = shrink_only;
      member.commit_failures = commit_failures;
      participants_[group_rank] = member;
      uint64_t gen = quorum_gen_;

      if (participants_.size() == world_size_) {
        participants_.clear();
        double timeout_s = static_cast<double>(timeout_ms) / 1000.0;
        std::thread([this, member, timeout_s] {
          run_quorum(member, timeout_s);
        }).detach();
      }

      while (quorum_gen_ == gen) {
        if (std::chrono::steady_clock::now() >= deadline || shutdown_) {
          failed = true;
          fail_code = shutdown_ ? ERR_SHUTDOWN : ERR_TIMEOUT;
          fail_msg = "manager quorum for group_rank " +
                     std::to_string(group_rank) +
                     (shutdown_ ? " aborted by shutdown" : " timed out");
          break;
        }
        cv_.wait_until(lock,
                       std::min(deadline, std::chrono::steady_clock::now() +
                                              std::chrono::milliseconds(100)));
      }
      if (!failed) {
        if (!latest_ok_) {
          failed = true;
          fail_code = ERR_UNKNOWN;
          fail_msg = latest_err_;
        } else {
          quorum = latest_;
        }
      }
    }

    if (failed) {
      send_error(conn, fail_code, fail_msg);
      return;
    }
    try {
      ManagerQuorumResult reply =
          compute_quorum_results(replica_id_, group_rank, quorum, init_sync);
      Writer w;
      reply.encode(w);
      send_frame(conn, MGR_QUORUM_RESP, w);
    } catch (const WireError& e) {
      send_error(conn, e.code, e.what());
    }
  }

  void run_quorum(const QuorumMember& requester, double timeout_s) {
    bool ok = false;
    Quorum quorum;
    std::string last_err = "unknown";
    // persistent lighthouse connection across rounds (reference keeps a
    // tonic channel, src/manager.rs:250-306); serialized by lh_fd_mu_
    std::lock_guard<std::mutex> fd_lock(lh_fd_mu_);
    for (int64_t attempt = 0; attempt <= quorum_retries_; ++attempt) {
      try {
        if (lh_fd_ < 0) lh_fd_ = dial(lighthouse_addr_, connect_timeout_s_);
        int fd = lh_fd_;
        Writer w;
        requester.encode(w);
        w.u64(static_cast<uint64_t>(timeout_s * 1000));
        set_recv_timeout(fd, timeout_s + 5.0);
        send_frame(fd, LH_QUORUM_REQ, w);
        auto [type, body] = recv_frame(fd);
        if (type == ERROR_FRAME) {
          Reader r(body.data(), body.size());
          ErrCode code = static_cast<ErrCode>(r.u8());
          throw WireError(code, r.str());
        }
        Reader r(body.data(), body.size());
        quorum = Quorum::decode(r);
        ok = true;
        break;
      } catch (const std::exception& e) {
        if (lh_fd_ >= 0) {
          ::close(lh_fd_);
          lh_fd_ = -1;
        }
        last_err = e.what();
        if (attempt < quorum_retries_) {
          double sleep_s =
              std::max(0.1, timeout_s / static_cast<double>(quorum_retries_ + 1));
          std::this_thread::sleep_for(std::chrono::duration<double>(sleep_s));
        }
      }
    }
    {
      std::lock_guard<std::mutex> lock(mu_);
      latest_ok_ = ok;
      latest_ = quorum;
      latest_err_ = ok ? "" : ("lighthouse quorum failed: " + last_err);
      quorum_gen_ += 1;
    }
    cv_.notify_all();
  }

  void handle_should_commit(int conn, Reader& r) {
    int64_t group_rank = r.i64();
    (void)r.i64();  // step (unchecked, matching the reference TODO)
    bool should_commit = r.boolean();
    uint64_t timeout_ms = r.u64();
    auto deadline =
        std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);

    bool decision = false;
    bool failed = false;
    ErrCode fail_code = ERR_TIMEOUT;
    std::string fail_msg;
    {
      std::unique_lock<std::mutex> lock(mu_);
      if (!should_commit) commit_failures_.insert(group_rank);
      commit_votes_.insert(group_rank);
      uint64_t gen = commit_gen_;

      if (commit_votes_.size() == world_size_) {
        commit_decision_ = commit_failures_.empty();
        commit_votes_.clear();
        commit_failures_.clear();
        commit_gen_ += 1;
        cv_.notify_all();
      }

      while (commit_gen_ == gen) {
        if (std::chrono::steady_clock::now() >= deadline || shutdown_) {
          failed = true;
          fail_code = shutdown_ ? ERR_SHUTDOWN : ERR_TIMEOUT;
          fail_msg = "should_commit for group_rank " +
                     std::to_string(group_rank) +
                     (shutdown_ ? " aborted by shutdown" : " timed out");
          break;
        }
        cv_.wait_until(lock,
                       std::min(deadline, std::chrono::steady_clock::now() +
                                              std::chrono::milliseconds(100)));
      }
      decision = commit_decision_;
    }

    if (failed) {
      send_error(conn, fail_code, fail_msg);
      return;
    }
    Writer w;
    w.boolean(decision);
    send_frame(conn, MGR_SHOULD_COMMIT_RESP, w);
  }

  std::string replica_id_;
  std::string lighthouse_addr_;
  std::string hostname_;
  std::string store_addr_;
  uint64_t world_size_;
  double heartbeat_interval_s_;
  double connect_timeout_s_;
  int64_t quorum_retries_;

  int listen_fd_ = -1;
  int port_ = 0;
  std::atomic<bool> shutdown_{false};
  std::thread accept_thread_;
  std::thread heartbeat_thread_;

  // guards participants_/checkpoint_metadata_/quorum_gen_/latest_ok_/
  // latest_/latest_err_/commit_votes_/commit_failures_/commit_gen_/
  // commit_decision_
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<int64_t, QuorumMember> participants_;
  std::map<int64_t, std::string> checkpoint_metadata_;
  uint64_t quorum_gen_ = 0;
  bool latest_ok_ = false;
  Quorum latest_;
  std::string latest_err_;
  std::set<int64_t> commit_votes_;
  std::set<int64_t> commit_failures_;
  uint64_t commit_gen_ = 0;
  bool commit_decision_ = false;
  ConnRegistry conns_;
  // guards lh_fd_
  std::mutex lh_fd_mu_;
  int lh_fd_ = -1;
};

}  // namespace tpuft
